"""ctypes front-end for the native C++ record loader.

The C++ side (``data/native/record_loader.cc``) is the framework's native
data-loader runtime: TFRecord framing, tf.Example wire parsing, libjpeg
decode and batch assembly on a worker thread pool, with batches landing in a
ring of preallocated buffers. This module:

  * builds the shared library on first use (g++, cached by mtime);
  * decides, from a feature/label spec pair, whether the fast path supports
    the dataset (``plan_for_specs``). Since round 6 the fast path covers
    sequences (given ``sequence_max_len``), varlen pad/clip, optional
    features, and multi-dataset zip; the Python-parser fallback list is
    PNG images only (plus structurally unparseable specs: unnamed or
    duplicate feature names, object dtype);
  * exposes :class:`NativeBatchedStream`, an iterator of ``(features,
    labels)`` SpecStruct batches matching BatchedExampleStream's contract.

Error delivery contract: creating a stream validates CONFIG only; the
C++ reader/worker threads start on the first ``next()``, so every
data-dependent error (missing file, corrupt record, decode failure,
frame-count mismatch) surfaces at iteration — deterministically, never
racing the constructor.

Parity target: the reference's input hot path is TF's C++ tf.data runtime
(/root/reference/utils/tfdata.py:527-575 — parallel_interleave + map with
num_parallel_calls + prefetch(AUTOTUNE)); this is the equivalent component,
sized to host cores via the ``threads`` knob.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec, bfloat16

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), 'native')
_SOURCE = os.path.join(_NATIVE_DIR, 'record_loader.cc')
_BUILD_LOCK = threading.Lock()
_LIB = None

# Field kinds, mirroring record_loader.cc's FieldKind.
_KIND_FLOAT = 0
_KIND_INT = 1
_KIND_IMAGE_FULL = 2
_KIND_IMAGE_COEF = 3
_KIND_IMAGE_COEF_SPARSE = 4
_KIND_IMAGE_COEF_PACKED = 5

# Bucket granularity (entries) for sparse coefficient streams: per-batch
# max entry counts are rounded up to a multiple of this before slicing, so
# the device-side unpack sees few distinct shapes (bounded jit cache) while
# transfer padding stays under ~7% at realistic densities.
SPARSE_BUCKET = 4096

# Bucket granularities for the PACKED wire ('coef_packed'): the nibble
# stream averages ~1 byte per AC nonzero (vs 2 for loose sparse), so a
# finer bucket keeps the padding share comparable; the escape stream is
# two orders of magnitude smaller and buckets finer still.
PACKED_BUCKET = 2048
ESCAPE_BUCKET = 256


def _so_path() -> str:
  return os.path.join(_NATIVE_DIR, '_record_loader.so')


def build_native(force: bool = False) -> str:
  """Compiles record_loader.cc into a shared library (cached by mtime)."""
  so = _so_path()
  with _BUILD_LOCK:
    if (not force and os.path.exists(so)
        and os.path.getmtime(so) >= os.path.getmtime(_SOURCE)):
      return so
    tmp = so + '.build.{}'.format(os.getpid())
    cmd = ['g++', '-O2', '-fPIC', '-shared', '-std=c++17', '-msse4.2',
           '-o', tmp, _SOURCE, '-ljpeg', '-lpthread']
    try:
      subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
      raise RuntimeError(
          'native loader build failed:\n{}'.format(e.stderr)) from e
    os.replace(tmp, so)  # atomic: concurrent builders race benignly
  return so


def _lib():
  global _LIB
  if _LIB is None:
    lib = ctypes.CDLL(build_native())
    lib.t2r_loader_create.restype = ctypes.c_void_p
    lib.t2r_loader_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.t2r_loader_last_error.restype = ctypes.c_char_p
    lib.t2r_loader_last_error.argtypes = [ctypes.c_void_p]
    lib.t2r_loader_num_buffers.restype = ctypes.c_int
    lib.t2r_loader_num_buffers.argtypes = [ctypes.c_void_p]
    lib.t2r_loader_buffer_size.restype = ctypes.c_longlong
    lib.t2r_loader_buffer_size.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.t2r_loader_buffer_ptr.restype = ctypes.c_void_p
    lib.t2r_loader_buffer_ptr.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.c_int]
    lib.t2r_loader_ring_size.restype = ctypes.c_int
    lib.t2r_loader_ring_size.argtypes = [ctypes.c_void_p]
    lib.t2r_loader_next.restype = ctypes.c_int
    lib.t2r_loader_next.argtypes = [ctypes.c_void_p]
    lib.t2r_loader_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.t2r_loader_destroy.argtypes = [ctypes.c_void_p]
    lib.t2r_loader_stats.restype = ctypes.c_longlong
    lib.t2r_loader_stats.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_longlong),
                                     ctypes.c_int]
    _LIB = lib
  return _LIB


# t2r_loader_stats slot order (record_loader.cc stats_snapshot).
_STAT_NAMES = ('records_read', 'bytes_read', 'reader_busy_us',
               'reader_wait_us', 'rows_parsed', 'parse_bytes',
               'worker_busy_us', 'worker_idle_us', 'n_workers',
               'completed_batches', 'min_worker_busy_us',
               'max_worker_busy_us')


class _Field:
  """One parsed field: config line + numpy view metadata."""

  def __init__(self, key: str, spec: TensorSpec, kind: int,
               dtype_size: int, shape: Tuple[int, ...],
               view_dtype, count: int = 0, seq_cap: int = 0,
               varlen: bool = False, optional: bool = False,
               dsi: int = 0, pad_value: float = 0.0):
    self.key = key            # flat spec key ('state/image')
    self.spec = spec
    self.kind = kind
    self.dtype_size = dtype_size
    self.shape = shape        # per-row output shape (per STEP for seqs)
    self.view_dtype = view_dtype
    self.count = count
    # > 0: SequenceExample feature_lists field with this step capacity;
    # rows come back [seq_cap, *shape] zero-padded + a per-row length.
    self.seq_cap = seq_cap
    # Varlen: on-disk value count may differ from the spec; the C++ side
    # clips extras / pads shortfalls with ``pad_value`` (parser.py
    # pad_or_clip semantics).
    self.varlen = varlen
    # Optional: records may omit the feature; a per-row presence buffer
    # rides along and _pack drops the key from any batch that is not
    # fully present (the Python parser's dense-batch semantics).
    self.optional = optional
    # Dataset index (multi-dataset zip): which zipped record this field
    # parses from.
    self.dsi = dsi
    self.pad_value = pad_value
    # Images: last three dims are H, W, C (rank-4 specs carry a leading
    # frame count, which travels in ``count``).
    h, w, c = shape[-3:] if kind in (
        _KIND_IMAGE_FULL, _KIND_IMAGE_COEF,
        _KIND_IMAGE_COEF_SPARSE, _KIND_IMAGE_COEF_PACKED) else (0, 0, 0)
    self.h, self.w, self.c = h, w, c

  def config_line(self) -> str:
    name = self.spec.name.encode('utf-8')
    return '{} {} {} {} {} {} {} {} {} {} {} {:.17g} {}'.format(
        len(name), self.kind, self.dtype_size, self.h, self.w, self.c,
        self.count, self.seq_cap, int(self.varlen), int(self.optional),
        self.dsi, float(self.pad_value), self.spec.name)


class NativeLoaderPlan:
  """Eligibility + field layout for a (feature_spec, label_spec) pair.

  ``dataset_keys`` orders the zip groups: field ``dsi`` indexes into it,
  and a stream built from this plan must provide one file list per key
  (a plain list when the only key is '').
  """

  def __init__(self, fields: List[_Field], feature_spec, label_spec,
               dataset_keys: Optional[List[str]] = None):
    self.fields = fields
    self.feature_spec = feature_spec
    self.label_spec = label_spec
    self.dataset_keys = list(dataset_keys or [''])


def coef_eligible(spec: TensorSpec) -> bool:
  """Can this image spec ship as DCT coefficients (split decode)?

  Baseline 4:2:0 constraints: rank-3 uint8 3-channel JPEG with both
  spatial dims divisible by 16. The ONE authority for coef eligibility —
  plan_for_specs and DeviceDecodePreprocessor both consult it.
  """
  shape = tuple(spec.shape or ())
  return (spec.is_encoded_image
          and spec.data_format in (None, 'jpeg', 'JPEG', 'jpg')
          and len(shape) == 3 and shape[-1] == 3
          and spec.dtype == np.uint8
          and shape[0] % 16 == 0 and shape[1] % 16 == 0)


def total_coefficients(spec: TensorSpec) -> int:
  """Flat DCT coefficient count of one 4:2:0 frame (y + cb + cr blocks)."""
  h, w = spec.shape[0], spec.shape[1]
  return ((h // 8) * (w // 8) + 2 * (h // 16) * (w // 16)) * 64


def sparse_capacity(spec: TensorSpec, density: float) -> int:
  """Entry capacity for a sparse coef stream at the given density budget."""
  total = total_coefficients(spec)
  cap = int(np.ceil(total * density / SPARSE_BUCKET)) * SPARSE_BUCKET
  return max(cap, SPARSE_BUCKET)


def packed_capacity(spec: TensorSpec, density: float) -> int:
  """Byte capacity of one packed nibble stream at the density budget.

  The packed wire spends ~1 byte per AC nonzero plus skip bytes, i.e.
  strictly less than the loose format's 1 delta byte per entry — so the
  same entry-count budget, taken as BYTES, over-provisions by design
  (the stream errors with a clear message on pathological overflow).
  Multiple of 8 so the C++ side's derived escape capacity (bytes / 8
  int16 entries) is exact.
  """
  return sparse_capacity(spec, density)


def packed_dc_count(spec: TensorSpec) -> int:
  """Blocks (= DC coefficients) of one 4:2:0 frame; always even."""
  h, w = spec.shape[0], spec.shape[1]
  return (h // 8) * (w // 8) + 2 * (h // 16) * (w // 16)


def plan_for_specs(feature_spec, label_spec,
                   image_mode: str = 'full',
                   sparse_density: float = 0.5,
                   sequence_max_len: Optional[int] = None
                   ) -> Optional[NativeLoaderPlan]:
  """Returns a plan if the native fast path supports these specs, else None.

  ``image_mode``: 'full' (decode to uint8 pixels), 'coef' (entropy-only
  decode; device finishes via data/jpeg_device.py — requires 4:2:0 JPEGs
  with dims divisible by 16), 'coef_sparse' (entropy decode + sparse
  delta/value packing of the ~88%-zero quantized coefficients — same
  device finish after a cumsum + scatter-add unpack, ~8x fewer bytes over
  the host->device link; see record_loader.cc decode_jpeg_coef_sparse),
  or 'coef_packed' (the bit-packed wire: nibble-coded AC entries, a
  nibble DC-delta plane, an int16 escape stream, and batch-hoisted quant
  tables — ~1.8x fewer bytes again vs 'coef_sparse', bit-exact the same
  coefficients; record_loader.cc decode_jpeg_coef_packed).

  ``sparse_density``: coef_sparse only — per-image entry capacity as a
  fraction of the total coefficient count. Realistic camera frames run
  ~12-14% nonzero; the 0.5 default leaves 3-4x headroom (the stream
  errors with a clear message if a pathological image overflows it).

  ``sequence_max_len``: step CAPACITY for SequenceExample feature_lists
  specs (``is_sequence``), e.g. the workload's episode length bound.
  Without it sequence specs fall back to the Python parser (the batch
  buffers are preallocated, so an upper bound is required); records with
  more steps fail with a clear error. Numeric (float/int) sequences only
  — bytes/JPEG steps fall back; derived ``<key>_length`` specs are
  produced by the stream, not read from disk.

  Varlen specs (``varlen_default_value`` set) are native for rank-1
  numeric tensors and rank-4 'full'-mode frame lists (clip/pad with the
  default value — parser.py pad_or_clip parity); optional specs
  (``is_optional``) are native everywhere except coef image modes, with
  the Python parser's dense-batch semantics (a batch where ANY record
  omits the feature drops the key). Specs with ``dataset_key`` plan as a
  multi-dataset zip: the stream then takes one file list per key. The
  remaining Python-parser fallbacks are PNG images and structurally
  unparseable specs (unnamed/duplicate names, object dtype).
  """
  feature_spec = specs_lib.flatten_spec_structure(feature_spec)
  label_spec = specs_lib.flatten_spec_structure(label_spec)
  fields: List[_Field] = []
  seen_names = set()
  sides = (('features', feature_spec), ('labels', label_spec))
  dataset_keys = sorted({(struct[key].dataset_key or '')
                         for _, struct in sides for key in struct
                         if struct[key].name is not None})
  if not dataset_keys:
    return None
  key_to_dsi = {k: i for i, k in enumerate(dataset_keys)}
  for side, struct in sides:
    for key in struct:
      spec = struct[key]
      if (key.endswith('_length') and key[:-len('_length')] in struct
          and struct[key[:-len('_length')]].is_sequence):
        # Derived length spec (algebra.add_sequence_length_specs): the
        # stream emits it from the parsed step counts.
        continue
      if spec.name is None or spec.name in seen_names:
        # The Python parser supports unnamed specs (skipped) and the same
        # on-disk feature bound under several spec keys (fanned out at pack
        # time, parser.py _pack_side); the native pack stage does neither,
        # and validate_and_pack would then raise on the missing keys every
        # batch. Fall back rather than fail downstream.
        return None
      optional = bool(spec.is_optional)
      varlen = spec.varlen_default_value is not None
      pad_value = float(spec.varlen_default_value or 0.0)
      dsi = key_to_dsi[spec.dataset_key or '']
      shape = tuple(spec.shape or ())
      if any(s is None for s in shape):
        return None
      full_key = side + '/' + key
      if spec.is_sequence:
        if not sequence_max_len or spec.is_encoded_image or varlen:
          # Varlen sequences pad the BATCH dim with the default value in
          # the Python parser — different semantics; keep them there.
          return None
        seen_names.add(spec.name)
        count = int(np.prod(shape)) if shape else 1
        if spec.dtype in (np.float32, bfloat16):
          fields.append(_Field(full_key, spec, _KIND_FLOAT, 4, shape,
                               np.float32, count,
                               seq_cap=int(sequence_max_len),
                               optional=optional, dsi=dsi))
        elif spec.dtype in (np.int64, np.int32, np.uint8, np.bool_):
          size = {np.dtype(np.int64): 8, np.dtype(np.int32): 4,
                  np.dtype(np.uint8): 1, np.dtype(np.bool_): 1}[
                      np.dtype(spec.dtype)]
          fields.append(_Field(full_key, spec, _KIND_INT, size, shape,
                               spec.dtype, count,
                               seq_cap=int(sequence_max_len),
                               optional=optional, dsi=dsi))
        else:
          return None
        continue
      if spec.is_encoded_image:
        if spec.data_format not in (None, 'jpeg', 'JPEG', 'jpg'):
          return None
        if len(shape) not in (3, 4) or spec.dtype != np.uint8 \
            or shape[-1] not in (1, 3):
          return None
        if varlen and (image_mode != 'full' or len(shape) != 4):
          return None  # varlen images are frame LISTS, full decode only
        if image_mode in ('coef', 'coef_sparse', 'coef_packed'):
          if not coef_eligible(spec) or optional or varlen:
            return None  # incl. rank-4: coef mode is single-frame only;
                         # no presence/pad machinery on the coef buffers
          if image_mode == 'coef_packed':
            fields.append(_Field(
                full_key, spec, _KIND_IMAGE_COEF_PACKED, 1, shape, np.uint8,
                count=packed_capacity(spec, sparse_density), dsi=dsi))
          elif image_mode == 'coef_sparse':
            fields.append(_Field(
                full_key, spec, _KIND_IMAGE_COEF_SPARSE, 1, shape, np.int8,
                count=sparse_capacity(spec, sparse_density), dsi=dsi))
          else:
            fields.append(_Field(full_key, spec, _KIND_IMAGE_COEF, 1, shape,
                                 np.int16, dsi=dsi))
        else:
          # Rank-4 [T, H, W, C]: a list of T encoded frames (episode
          # data, e.g. seq2act) — strict count unless varlen (clip/pad);
          # count carries T to the C++ side.
          frames = shape[0] if len(shape) == 4 else 0
          fields.append(_Field(full_key, spec, _KIND_IMAGE_FULL, 1, shape,
                               np.uint8, count=frames, varlen=varlen,
                               optional=optional, dsi=dsi,
                               pad_value=pad_value))
      elif spec.dtype == np.dtype(object):
        return None
      elif spec.dtype in (np.float32, bfloat16):
        if varlen and len(shape) != 1:
          return None  # parser pads/clips dim 0 of the FLAT list: only
                       # rank-1 specs are well-defined
        count = int(np.prod(shape)) if shape else 1
        fields.append(_Field(full_key, spec, _KIND_FLOAT, 4, shape,
                             np.float32, count, varlen=varlen,
                             optional=optional, dsi=dsi,
                             pad_value=pad_value))
      elif spec.dtype in (np.int64, np.int32, np.uint8, np.bool_):
        if varlen and len(shape) != 1:
          return None
        size = {np.dtype(np.int64): 8, np.dtype(np.int32): 4,
                np.dtype(np.uint8): 1, np.dtype(np.bool_): 1}[
                    np.dtype(spec.dtype)]
        count = int(np.prod(shape)) if shape else 1
        fields.append(_Field(full_key, spec, _KIND_INT, size, shape,
                             spec.dtype, count, varlen=varlen,
                             optional=optional, dsi=dsi,
                             pad_value=pad_value))
      else:
        return None
      seen_names.add(spec.name)
  if not fields:
    return None
  # Sequence streams emit derived <key>_length tensors; the validation
  # specs must include them (idempotent when the caller's spec already
  # went through add_sequence_length_specs).
  return NativeLoaderPlan(fields,
                          specs_lib.add_sequence_length_specs(feature_spec),
                          specs_lib.add_sequence_length_specs(label_spec),
                          dataset_keys=dataset_keys)


class NativeBatchedStream:
  """Iterator of (features, labels) batches from the native loader.

  Matches BatchedExampleStream's contract (data/pipeline.py:129). With
  ``copy=False`` the yielded arrays are zero-copy views into the loader's
  ring buffers, valid until ``ring - 1`` further batches have been drawn;
  the default ``copy=True`` hands out owned arrays.
  """

  def __init__(self, plan: NativeLoaderPlan,
               filenames,
               batch_size: int,
               shuffle: bool = False,
               shuffle_buffer: int = 500,
               num_epochs: Optional[int] = None,
               seed: Optional[int] = None,
               num_threads: Optional[int] = None,
               ring: int = 3,
               verify_crc: bool = False,
               copy: bool = True,
               validate: bool = True,
               bucket_sparse: bool = True):
    """``filenames``: a sequence of record paths, or — for a plan whose
    specs carry ``dataset_key``s (multi-dataset zip) — a dict mapping
    each of ``plan.dataset_keys`` to its file list; row r of every batch
    is then assembled from one record of EACH dataset (zip ends with the
    shortest), exactly like BatchedExampleStream's dataset_map path."""
    self._plan = plan
    self._batch_size = int(batch_size)
    self._copy = copy
    self._validate = validate
    # Multi-process SPMD callers MUST pass bucket_sparse=False: each host
    # buckets from its OWN batch's max entry count, and divergent per-host
    # buckets give make_array_from_process_local_data inconsistent global
    # shapes (input_generators.py passes process_count()==1 through here).
    self._bucket_sparse = bool(bucket_sparse)
    self._lib = _lib()
    threads = num_threads or max(1, min(16, (os.cpu_count() or 2)))
    if isinstance(filenames, dict):
      missing = [k for k in plan.dataset_keys if k not in filenames]
      if missing:
        raise ValueError(
            'filenames dict is missing dataset keys {} (plan expects '
            '{}).'.format(missing, plan.dataset_keys))
      file_groups = [list(filenames[k]) for k in plan.dataset_keys]
    else:
      if len(plan.dataset_keys) > 1:
        raise ValueError(
            'plan zips datasets {}; pass filenames as a dict keyed by '
            'dataset key.'.format(plan.dataset_keys))
      file_groups = [list(filenames)]
    lines = [
        'batch_size {}'.format(self._batch_size),
        'ring {}'.format(ring),
        'threads {}'.format(threads),
        'shuffle {}'.format(1 if shuffle else 0),
        'shuffle_buffer {}'.format(shuffle_buffer),
        'seed {}'.format(-1 if seed is None else seed),
        'epochs {}'.format(-1 if num_epochs is None else num_epochs),
        'verify_crc {}'.format(1 if verify_crc else 0),
    ]
    for group in file_groups:
      lines.append('group {}'.format(len(group)))
      lines.extend(group)
    lines.append('fields {}'.format(len(plan.fields)))
    lines.extend(f.config_line() for f in plan.fields)
    config = '\n'.join(lines).encode('utf-8')
    self._handle = self._lib.t2r_loader_create(config, len(config))
    if not self._handle:
      raise RuntimeError('native loader creation failed')
    # Create-time errors are CONFIG errors only (parse/allocate run
    # synchronously); the worker threads start lazily on the first
    # next(), so data/decode errors surface at iteration — the one
    # documented error-surfacing point.
    err = self._lib.t2r_loader_last_error(self._handle)
    if err:
      msg = err.decode('utf-8', 'replace')
      self._lib.t2r_loader_destroy(self._handle)
      self._handle = None
      raise RuntimeError('native loader: ' + msg)
    self._ring = self._lib.t2r_loader_ring_size(self._handle)
    self._views = self._build_views()
    self._held_slot = -1
    self._closed = False
    # Pipeline X-ray publishing (observability/pipeline_xray.py): the C++
    # loader's cumulative stats become pipeline/{read,decode}/* counter
    # DELTAS at every batch, so the registry stays monotonic even across
    # several streams in one process (each stream publishes only what it
    # added since its own last publish).
    self._published_stats = {name: 0 for name in _STAT_NAMES}
    self._stage_meters = None

  def stats(self) -> Dict[str, int]:
    """Cumulative loader-side stats (record_loader.cc stats_snapshot).

    Zeros before the first ``next()`` — reading stats never launches the
    reader/worker threads (the lazy-launch error-delivery contract).
    After ``close()`` the last published values are gone; zeros again.
    """
    if not self._handle:
      return {name: 0 for name in _STAT_NAMES}
    buf = (ctypes.c_longlong * len(_STAT_NAMES))()
    n = int(self._lib.t2r_loader_stats(self._handle, buf, len(_STAT_NAMES)))
    return {name: int(buf[i]) for i, name in enumerate(_STAT_NAMES[:n])}

  def _publish_stats(self) -> None:
    from tensor2robot_tpu.observability import get_registry
    from tensor2robot_tpu.observability.pipeline_xray import (
        DECODE_IDLE_COUNTER,
        DECODE_WORKERS_GAUGE,
        StageMeter,
    )

    if self._stage_meters is None:
      registry = get_registry()
      self._stage_meters = (StageMeter('read', registry),
                            StageMeter('decode', registry),
                            registry.counter(DECODE_IDLE_COUNTER),
                            registry.gauge(DECODE_WORKERS_GAUGE))
    read_meter, decode_meter, idle_counter, workers_gauge = \
        self._stage_meters
    stats = self.stats()
    delta = {name: stats[name] - self._published_stats.get(name, 0)
             for name in stats}
    self._published_stats = stats
    read_meter.add(examples=delta.get('records_read', 0),
                   nbytes=delta.get('bytes_read', 0),
                   busy_s=delta.get('reader_busy_us', 0) / 1e6)
    decode_meter.add(examples=delta.get('rows_parsed', 0),
                     nbytes=delta.get('parse_bytes', 0),
                     busy_s=delta.get('worker_busy_us', 0) / 1e6)
    idle = delta.get('worker_idle_us', 0)
    if idle > 0:
      idle_counter.inc(idle / 1e6)
    workers_gauge.set(float(stats.get('n_workers', 0)))

  # -- buffer views ----------------------------------------------------------

  def _buffer_layout(self):
    """(field, sub) per buffer index — mirrors record_loader.cc's order."""
    layout = []
    for f in self._plan.fields:
      if f.seq_cap > 0:
        layout.extend([(f, ''), (f, 'len')])
      elif f.kind == _KIND_IMAGE_COEF:
        layout.extend([(f, 'y'), (f, 'cb'), (f, 'cr'), (f, 'qt')])
      elif f.kind == _KIND_IMAGE_COEF_SPARSE:
        layout.extend([(f, 'sd'), (f, 'sv'), (f, 'qt'), (f, 'n')])
      elif f.kind == _KIND_IMAGE_COEF_PACKED:
        layout.extend([(f, 'pw'), (f, 'se'), (f, 'dcn'), (f, 'qt'),
                       (f, 'n'), (f, 'ne')])
      else:
        layout.append((f, ''))
      if f.optional:
        layout.append((f, 'p'))  # per-row presence flags
    return layout

  def _build_views(self):
    layout = self._buffer_layout()
    n_bufs = self._lib.t2r_loader_num_buffers(self._handle)
    if n_bufs != len(layout):
      raise RuntimeError('buffer layout mismatch: {} vs {}'.format(
          n_bufs, len(layout)))
    views = []
    B = self._batch_size
    for slot in range(self._ring):
      slot_views = []
      for buf, (f, sub) in enumerate(layout):
        ptr = self._lib.t2r_loader_buffer_ptr(self._handle, slot, buf)
        size = self._lib.t2r_loader_buffer_size(self._handle, buf)
        if sub == '':
          if f.kind == _KIND_IMAGE_FULL:
            shape = (B,) + f.shape
            dtype = np.uint8
          elif f.seq_cap > 0:
            shape = (B, f.seq_cap) + f.shape
            dtype = f.view_dtype
          else:
            shape = (B,) + f.shape
            dtype = f.view_dtype
        elif sub == 'len':
          shape = (B,)
          dtype = np.int32
        elif sub == 'p':
          shape = (B,)
          dtype = np.uint8
        elif sub == 'y':
          shape = (B, f.h // 8, f.w // 8, 64)
          dtype = np.int16
        elif sub in ('cb', 'cr'):
          shape = (B, f.h // 16, f.w // 16, 64)
          dtype = np.int16
        elif sub == 'sd':
          shape = (B, f.count)
          dtype = np.uint8
        elif sub == 'sv':
          shape = (B, f.count)
          dtype = np.int8
        elif sub == 'pw':
          shape = (B, f.count)
          dtype = np.uint8
        elif sub == 'se':
          shape = (B, f.count // 4)
          dtype = np.int16
        elif sub == 'dcn':
          shape = (B, packed_dc_count(f.spec) // 2)
          dtype = np.uint8
        elif sub in ('n', 'ne'):
          shape = (B,)
          dtype = np.int32
        else:  # qt
          shape = (B, 3, 64)
          dtype = np.uint16
        expect = int(np.prod(shape)) * np.dtype(dtype).itemsize
        if expect != size:
          raise RuntimeError(
              'buffer {} size {} != expected {}'.format(buf, size, expect))
        arr = np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)),
            shape=(size,)).view(dtype).reshape(shape)
        slot_views.append(arr)
      views.append(slot_views)
    return views

  # -- iteration -------------------------------------------------------------

  def _pack(self, slot: int):
    layout = self._buffer_layout()
    # Sparse coef streams: slice the capacity-sized delta/value buffers to
    # the batch's bucketed max entry count BEFORE they leave the loader —
    # the whole point of the format is that the host->device transfer pays
    # for actual entries, not capacity padding. The slice-copy makes these
    # arrays owned regardless of the ``copy`` setting.
    buckets: Dict[str, int] = {}
    esc_buckets: Dict[str, int] = {}
    for buf, (f, sub) in enumerate(layout):
      if sub == 'n':
        if f.kind == _KIND_IMAGE_COEF_PACKED:
          # Packed wire: f.count is the BYTE capacity of the nibble
          # stream; its own (finer) bucket granularity.
          if not self._bucket_sparse:
            buckets[f.key] = int(f.count)
            continue
          max_n = int(self._views[slot][buf].max())
          buckets[f.key] = max(
              PACKED_BUCKET,
              -(-max_n // PACKED_BUCKET) * PACKED_BUCKET)
          buckets[f.key] = min(buckets[f.key], int(f.count))
          continue
        if not self._bucket_sparse:
          buckets[f.key] = int(f.count)  # full capacity: host-invariant
          continue
        max_n = int(self._views[slot][buf].max())
        buckets[f.key] = max(
            SPARSE_BUCKET,
            -(-max_n // SPARSE_BUCKET) * SPARSE_BUCKET)
      elif sub == 'ne':
        if not self._bucket_sparse:
          esc_buckets[f.key] = int(f.count) // 4
          continue
        max_n = int(self._views[slot][buf].max())
        esc_buckets[f.key] = min(
            max(ESCAPE_BUCKET, -(-max_n // ESCAPE_BUCKET) * ESCAPE_BUCKET),
            int(f.count) // 4)
    # Sequence fields: slice the capacity-padded step dim to the batch's
    # max actual length — the Python parser's pad-to-longest-in-batch
    # semantics (parser.py parse_batch).
    seq_max: Dict[str, int] = {}
    seq_lengths: Dict[str, np.ndarray] = {}
    for buf, (f, sub) in enumerate(layout):
      if sub == 'len':
        lengths = self._views[slot][buf]
        seq_lengths[f.key] = lengths.astype(np.int64)
        seq_max[f.key] = max(1, int(lengths.max()))
    # Optional fields: the Python parser drops a key from any batch where
    # SOME record omitted it (a batch is dense). The C++ side reports
    # per-row presence; a not-fully-present batch drops the key here.
    dropped = set()
    for buf, (f, sub) in enumerate(layout):
      if sub == 'p' and not self._views[slot][buf].all():
        dropped.add(f.key)
    by_key: Dict[str, np.ndarray] = {}
    for buf, (f, sub) in enumerate(layout):
      arr = self._views[slot][buf]
      if sub in ('len', 'p') or f.key in dropped:
        continue  # 'len' emitted as <key>_length below
      if sub in ('n', 'ne') and f.kind == _KIND_IMAGE_COEF_PACKED:
        continue  # host-side bucketing inputs only; the device unpack
                  # needs no counts (padding bytes are no-ops)
      if sub in ('sd', 'sv'):
        # .copy(), NOT ascontiguousarray: when the bucket equals the full
        # capacity the slice is already contiguous and ascontiguousarray
        # would return a live VIEW into the recycled ring buffer.
        arr = arr[:, :buckets[f.key]].copy()
      elif sub == 'pw':
        arr = arr[:, :buckets[f.key]].copy()
      elif sub == 'se':
        arr = arr[:, :esc_buckets[f.key]].copy()
      elif sub == 'qt' and f.kind == _KIND_IMAGE_COEF_PACKED:
        arr = self._hoisted_quant_table(f, arr)
      elif f.seq_cap > 0 and sub == '':
        arr = arr[:, :seq_max[f.key]].copy()
      elif self._copy:
        arr = arr.copy()
      key = f.key if not sub else f.key + '/' + sub
      if sub == '' and f.spec.dtype == bfloat16:
        arr = arr.astype(bfloat16)
      by_key[key] = arr
      if f.seq_cap > 0 and sub == '':
        by_key[f.key + '_length'] = seq_lengths[f.key]
    features = SpecStruct()
    labels = SpecStruct()
    for key, arr in by_key.items():
      side, rest = key.split('/', 1)
      (features if side == 'features' else labels)[rest] = arr
    if self._validate:
      coef = any(f.kind in (_KIND_IMAGE_COEF, _KIND_IMAGE_COEF_SPARSE,
                            _KIND_IMAGE_COEF_PACKED)
                 for f in self._plan.fields)
      if not coef:  # coef outputs intentionally mismatch the image specs
        features = specs_lib.validate_and_pack(
            self._plan.feature_spec, features, ignore_batch=True)
        if len(self._plan.label_spec):
          labels = specs_lib.validate_and_pack(
              self._plan.label_spec, labels, ignore_batch=True)
    return features, labels

  def _hoisted_quant_table(self, f: _Field, qt: np.ndarray) -> np.ndarray:
    """Batch-uniform quant table, hoisted to ONE [1, 3, 64] wire array.

    The packed wire contract (docs/performance.md "Transfer path"): the
    whole batch shares one set of quantization tables, so 384 bytes per
    example leave the wire. Rows whose tables are all-zero are empty
    payloads (the C++ side's "no table" sentinel) and are skipped; a
    genuine mismatch — a dataset mixing JPEG qualities — is a hard error
    at iteration naming the remedy (image_mode='coef_sparse' ships
    per-example tables). An all-empty batch ships 1s, matching the other
    coef modes' well-defined-dequant convention for zero images.
    """
    flat = qt.reshape(qt.shape[0], -1)
    present = flat.any(axis=1)
    if not present.any():
      return np.ones((1,) + qt.shape[1:], np.uint16)
    first = np.argmax(present)
    if not (flat[present] == flat[first]).all():
      raise RuntimeError(
          "native loader: image_coef_packed requires batch-uniform JPEG "
          "quantization tables for '{}' (the packed wire ships ONE table "
          "per batch); this dataset mixes qualities — use "
          "image_mode='coef_sparse' instead.".format(f.key))
    return qt[first:first + 1].copy()

  def __iter__(self):
    import time

    from tensor2robot_tpu.observability import get_registry
    from tensor2robot_tpu.observability.spans import SPAN_BUCKETS_MS

    pack_ms = get_registry().histogram('pipeline/batch/pack_ms',
                                       bounds=SPAN_BUCKETS_MS)
    while True:
      slot = self._lib.t2r_loader_next(self._handle)
      if slot == -1:
        self._publish_stats()
        self._release_held()
        return
      if slot < 0:
        err = self._lib.t2r_loader_last_error(self._handle)
        raise RuntimeError('native loader: ' +
                           (err or b'?').decode('utf-8', 'replace'))
      try:
        t_pack = time.perf_counter()
        batch = self._pack(slot)
        # Busy-only histogram: the pack rows are already counted by the
        # decode stage, so a batch-stage examples counter here would
        # double-count them in the X-ray capacity table.
        pack_ms.record((time.perf_counter() - t_pack) * 1e3)
        self._publish_stats()
      finally:
        if self._copy:
          self._lib.t2r_loader_release(self._handle, slot)
        else:
          # Zero-copy: hold this slot until the NEXT batch is drawn so the
          # consumer can use the views for one full step.
          self._release_held()
          self._held_slot = slot
      yield batch

  def _release_held(self):
    if self._held_slot >= 0:
      self._lib.t2r_loader_release(self._handle, self._held_slot)
      self._held_slot = -1

  def close(self):
    if not self._closed and self._handle:
      self._closed = True
      self._lib.t2r_loader_destroy(self._handle)
      self._handle = None

  def __del__(self):
    try:
      self.close()
    except Exception:  # pragma: no cover - interpreter teardown
      pass


def native_loader_enabled() -> bool:
  """Env switch: T2R_NATIVE_LOADER=0 disables the fast path."""
  return os.environ.get('T2R_NATIVE_LOADER', '1') not in ('0', 'false', '')
