"""Pipeline parallelism: GPipe microbatching over a 'pipe' mesh axis.

The reference scales only by data parallelism (SURVEY.md §2.9); pipeline
parallelism completes the framework's dp/fsdp/tp/sp/ep axis family for
models whose layer stacks exceed one device's HBM.

TPU-native shape: S pipeline stages live on S mesh shards. Inside one
``shard_map``, every device runs the same ``lax.scan`` over
``T = M + S - 1`` ticks (M microbatches); at each tick a device applies
its resident stage to either the next microbatch (stage 0) or the
activation received from its predecessor, then passes the result along
the ring with ``lax.ppermute`` — the classic collective-permute pipeline,
with the bubble (S - 1 idle ticks) explicit in T. The last stage
predicated-writes its outputs into the result buffer, which a masked
``psum`` replicates to all shards. Autodiff composes: ``ppermute``'s
transpose is the reverse permute and ``scan`` stores per-tick residuals,
so ``jax.grad`` through ``pipeline_apply`` runs the backward pipeline in
reverse stage order (pass ``remat=True`` to trade the scan's stored
per-tick residuals for recompute via ``jax.checkpoint``).

Constraints (documented, asserted): uniform activation shape across
stages (true of transformer blocks), stage params stacked on a leading
S dim, microbatch count M >= 1. Schedule is GPipe (fill-drain), not
1F1B — at the scale this framework targets (S <= 8 stages) the bubble
fraction (S-1)/(M+S-1) is controlled by raising M.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tensor2robot_tpu.parallel import collectives
from tensor2robot_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stage_params: Any,
                   x: jnp.ndarray,
                   mesh: Mesh,
                   axis: str = PIPE_AXIS,
                   remat: bool = False) -> jnp.ndarray:
  """Applies S stacked stages to M microbatches, pipelined over ``axis``.

  Args:
    stage_fn: ``(params_for_one_stage, activation [mb, ...]) -> [mb, ...]``
      — same activation shape in and out (uniform-width pipeline).
    stage_params: pytree whose leaves lead with dim S == mesh.shape[axis];
      leaf ``i`` holds stage i's params.
    x: ``[M, mb, ...]`` microbatched input.
    mesh: mesh containing ``axis``.
    remat: rematerialize each stage in the backward (``jax.checkpoint``
      around ``stage_fn``) — the scan otherwise stores every tick's
      stage residuals, O(T) activation memory per device; with remat it
      stores only the tick inputs and recomputes, the standard GPipe
      memory/compute trade.

  Returns:
    ``[M, mb, ...]`` outputs of the final stage (replicated over ``axis``).
  """
  if axis not in mesh.shape:
    raise ValueError('mesh has no {!r} axis (axes: {}).'.format(
        axis, tuple(mesh.axis_names)))
  s_count = int(mesh.shape[axis])
  m_count = int(x.shape[0])
  for leaf in jax.tree_util.tree_leaves(stage_params):
    if not getattr(leaf, 'shape', ()) or leaf.shape[0] != s_count:
      raise ValueError(
          'stage_params leaves must lead with the stage count {}; got '
          'leaf shape {}.'.format(s_count, leaf.shape))

  run_stage = jax.checkpoint(stage_fn) if remat else stage_fn
  param_spec = jax.tree.map(lambda _: P(axis), stage_params)
  # Data parallelism composes INSIDE the shard_map: the per-microbatch
  # batch dim of x shards over 'data' (when present and divisible), so
  # each data replica pipelines only its slice — all collectives below run
  # over the pipe axis only, which keeps the mb-dim sharding legal.
  data_size = int(mesh.shape.get(DATA_AXIS, 1))
  mb_axis = (DATA_AXIS
             if data_size > 1 and x.shape[1] % data_size == 0 else None)
  io_spec = P(None, mb_axis)

  @collectives.sharded_fn(mesh, in_specs=(param_spec, io_spec),
                          out_specs=io_spec)
  def _run(params, x_all):
    stage = jax.lax.axis_index(axis)
    local_params = jax.tree.map(lambda p: p[0], params)  # [1,...] -> stage's

    def tick(carry, t):
      act, y = carry
      mb_in = jax.lax.dynamic_index_in_dim(
          x_all, jnp.clip(t, 0, m_count - 1), 0, keepdims=False)
      cur = jnp.where(stage == 0, mb_in, act)
      out = run_stage(local_params, cur)
      nxt = collectives.ring_permute(out, axis)
      idx = t - (s_count - 1)
      write = (idx >= 0) & (stage == s_count - 1)
      slot = jnp.clip(idx, 0, m_count - 1)
      prev = jax.lax.dynamic_index_in_dim(y, slot, 0, keepdims=False)
      y = jax.lax.dynamic_update_index_in_dim(
          y, jnp.where(write, out, prev), slot, 0)
      return (nxt, y), None

    act0 = jnp.zeros_like(x_all[0])
    y0 = jnp.zeros_like(x_all)
    (_, y), _ = jax.lax.scan(tick, (act0, y0),
                             jnp.arange(m_count + s_count - 1))
    # Replicate the last stage's buffer to every pipe shard.
    return collectives.psum(
        jnp.where(stage == s_count - 1, y, jnp.zeros_like(y)), axis)

  return _run(stage_params, x)


def microbatch(x: jnp.ndarray, num_microbatches: int) -> jnp.ndarray:
  """[B, ...] -> [M, B/M, ...] for pipeline_apply."""
  b = x.shape[0]
  if b % num_microbatches:
    raise ValueError('batch {} not divisible into {} microbatches.'.format(
        b, num_microbatches))
  return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])


def unmicrobatch(y: jnp.ndarray) -> jnp.ndarray:
  """Inverse of :func:`microbatch`."""
  return y.reshape((y.shape[0] * y.shape[1],) + y.shape[2:])
