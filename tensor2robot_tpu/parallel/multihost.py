"""Multi-host (multi-process) training: init, per-host data, checkpoints.

SURVEY §2.9's DCN row: the reference scales across hosts through TF1's
gRPC/TF_CONFIG machinery (utils/train_eval.py:552,
models/abstract_model.py:845-851); here multi-host is JAX's native
multi-process model — one controller process per host, a global mesh over
all devices, per-host input shards assembled into global arrays
(parallel/sharding.py shard_batch -> make_array_from_process_local_data),
and Orbax writing a sharded checkpoint cooperatively from every host.

``python -m tensor2robot_tpu.parallel.multihost --process_id=K ...`` runs
a self-contained two-host dry run on CPU devices — the executable proof
(driven by tests/test_multihost.py) that distributed init + per-host data
+ mesh-sharded training + multi-host checkpointing compose. The same code
path serves real pods: only coordinator_address and the device platform
change.
"""

from __future__ import annotations

import argparse
import os
from typing import Optional


def initialize(coordinator_address: str, num_processes: int,
               process_id: int,
               local_device_count: Optional[int] = None) -> None:
  """jax.distributed.initialize with optional CPU device virtualization.

  Must run before any other JAX call in the process. On TPU pods the
  arguments are auto-detected and this reduces to
  ``jax.distributed.initialize()``.
  """
  if local_device_count is not None:
    os.environ['XLA_FLAGS'] = (
        os.environ.get('XLA_FLAGS', '') +
        ' --xla_force_host_platform_device_count={}'.format(
            local_device_count))
  import jax

  jax.distributed.initialize(coordinator_address=coordinator_address,
                             num_processes=num_processes,
                             process_id=process_id)


def multihost_dryrun(workdir: str, num_processes: int, process_id: int,
                     train_steps: int = 2) -> None:
  """Train a mock model across all processes' devices; checkpoint; verify.

  Asserts (a) every host sees the global device count, (b) per-host data
  shards assemble into one global batch (each host reads DIFFERENT files),
  (c) the jitted step runs with gradients psummed across hosts, (d) the
  Orbax checkpoint written cooperatively restores to identical params on
  every host, (e — ISSUE 9) each host emitted its OWN
  ``telemetry.<process_index>.jsonl`` under the SHARED model_dir (two
  processes appending one file would interleave torn lines), stamped
  with its identity, and host 0's fleet view federates every host's
  stream, and (f — ISSUE 15) the train step resolves through the shared
  ``CompiledArtifact`` store: host 0 AOT-compiles and PERSISTS the
  executable behind a barrier, hosts 1..N then bind by DESERIALIZING it
  — their ``jax/compiles`` delta across the bind is asserted 0, closing
  ROADMAP item 4's shared-autotuner/compile-cache clause (N hosts, one
  compile).
  """
  import jax
  import jax.numpy as jnp
  import numpy as np
  from jax.experimental import multihost_utils

  from tensor2robot_tpu import parallel
  from tensor2robot_tpu.data import tfrecord, wire
  from tensor2robot_tpu.data.input_generators import (
      DefaultRecordInputGenerator,
  )
  from tensor2robot_tpu.observability import get_registry
  from tensor2robot_tpu.trainer import Trainer
  from tensor2robot_tpu.utils.mocks import MockT2RModel

  assert jax.process_count() == num_processes, (
      jax.process_count(), num_processes)
  n_local = len(jax.local_devices())
  n_global = len(jax.devices())
  assert n_global == n_local * num_processes

  # Each host writes (then reads) its OWN shard files — the per-host input
  # contract (ref utils/tfdata.py:43-66, PER_HOST_V2).
  model = MockT2RModel(device_type='cpu')
  feature_spec = model.preprocessor.get_in_feature_specification('train')
  label_spec = model.preprocessor.get_in_label_specification('train')
  rng = np.random.RandomState(process_id)
  records = []
  for _ in range(64):
    x = rng.rand(8).astype(np.float32)
    y = np.asarray([float(x.sum() > 4.0)], np.float32)
    records.append(wire.build_example(
        {'measured_position': x, 'valid_position': y}))
  shard_dir = os.path.join(workdir, 'shards')
  os.makedirs(shard_dir, exist_ok=True)
  # All shard files exist for all hosts; host K reads files[K::N].
  path = os.path.join(shard_dir, 'data-{:05d}.tfrecord'.format(process_id))
  tfrecord.write_records(path, records)
  multihost_utils.sync_global_devices('shards_written')

  del feature_spec, label_spec
  mesh = parallel.create_mesh({'data': n_global})
  global_batch = 4 * n_global
  generator = DefaultRecordInputGenerator(
      file_patterns=os.path.join(shard_dir, 'data-*.tfrecord'),
      batch_size=global_batch // num_processes)
  model_dir = os.path.join(workdir, 'model')
  trainer = Trainer(model, model_dir, mesh=mesh, async_checkpoints=False,
                    save_checkpoints_steps=train_steps,
                    log_every_n_steps=10**9,
                    use_compiled_artifacts=True,
                    artifact_workload='multihost_step',
                    tuning_cache_path=os.path.join(workdir,
                                                   'compile_cache.json'))
  # ISSUE 15 satellite: the train step resolves through the SHARED
  # CompiledArtifact store. Host 0 AOT-compiles and persists the
  # executable while everyone else waits at the barrier; hosts 1..N
  # then bind by DESERIALIZING it — with their jax/compiles delta
  # across the bind asserted 0 (N hosts, ONE compile: ROADMAP item 4's
  # shared-autotuner/compile-cache clause). bind_train_step never
  # executes the (collective) step, which is what makes the stagger
  # legal before the first synchronized train step below.
  generator.set_specification_from_model(model, 'train')
  bind_features, bind_labels = next(generator.create_dataset_iterator(
      mode='train', shard_index=process_id, num_shards=num_processes))
  registry = get_registry()
  if process_id == 0:
    artifact = trainer.bind_train_step(bind_features, bind_labels)
    assert artifact is not None and not artifact.from_cache, (
        'host 0 must compile + persist the shared executable', artifact)
  multihost_utils.sync_global_devices('artifact_persisted')
  if process_id != 0:
    compiles_before = float(registry.scalars().get('jax/compiles', 0.0))
    artifact = trainer.bind_train_step(bind_features, bind_labels)
    compiles_delta = float(
        registry.scalars().get('jax/compiles', 0.0)) - compiles_before
    assert artifact is not None and artifact.from_cache, (
        'follower must deserialize host 0\'s persisted executable',
        artifact)
    assert compiles_delta == 0.0, (
        'follower bind must not compile: jax/compiles delta %r'
        % compiles_delta)
  multihost_utils.sync_global_devices('artifact_bound')
  # Per-host file shards come from the process-aware train() defaults.
  state = trainer.train(generator, max_train_steps=train_steps)
  assert int(jax.device_get(state.step)) == train_steps

  # Fleet observatory: this process wrote ITS stream (indexed, stamped)…
  from tensor2robot_tpu.observability import fleet as fleet_lib
  from tensor2robot_tpu.observability import telemetry_file

  own_stream = os.path.join(
      model_dir, 'telemetry.{}.jsonl'.format(process_id))
  assert os.path.exists(own_stream), own_stream
  own_records = telemetry_file.read_telemetry(own_stream)
  assert own_records and all(
      r.get('process_index') == process_id and
      r.get('process_count') == num_processes for r in own_records), (
          'per-host records missing their identity stamp')
  multihost_utils.sync_global_devices('telemetry_written')
  # …and host 0 federates every host's stream into one fleet view.
  if process_id == 0:
    fleet = fleet_lib.read_fleet(model_dir)
    assert sorted(fleet['hosts']) == list(range(num_processes)), (
        sorted(fleet['hosts']), num_processes)
    summary = fleet_lib.fleet_summary(model_dir)
    assert summary['host_count'] == num_processes, summary

  # Params must agree across hosts (the gradient psum is global).
  flat = jax.tree_util.tree_leaves(jax.device_get(state.params))
  checksum = np.asarray([float(np.sum(np.abs(leaf))) for leaf in flat],
                        np.float32)
  all_sums = np.asarray(multihost_utils.process_allgather(checksum))
  assert np.allclose(all_sums, all_sums[0], rtol=1e-6), all_sums
  trainer.close()

  # Restore the cooperatively-written checkpoint in a fresh Trainer and
  # compare to the live state (init_state restores when a checkpoint
  # exists; all hosts participate in the sharded Orbax restore).
  generator.set_specification_from_model(model, 'train')
  features, labels = next(generator.create_dataset_iterator(
      mode='train', shard_index=process_id, num_shards=num_processes))
  trainer2 = Trainer(model, model_dir, mesh=mesh, async_checkpoints=False,
                     save_checkpoints_steps=10**9, log_every_n_steps=10**9)
  restored = trainer2.init_state(features, labels)
  assert int(jax.device_get(restored.step)) == train_steps
  r_flat = jax.tree_util.tree_leaves(jax.device_get(restored.params))
  for a, b in zip(flat, r_flat):
    np.testing.assert_allclose(a, b, rtol=1e-6)
  trainer2.close()
  multihost_utils.sync_global_devices('done')

  marker = os.path.join(workdir, 'ok_{}'.format(process_id))
  with open(marker, 'w') as f:
    f.write('multihost dryrun ok: {} hosts x {} devices\n'.format(
        num_processes, n_local))


def main(argv=None):
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument('--workdir', required=True)
  parser.add_argument('--coordinator', default='localhost:9456')
  parser.add_argument('--num_processes', type=int, default=2)
  parser.add_argument('--process_id', type=int, required=True)
  parser.add_argument('--local_device_count', type=int, default=4)
  parser.add_argument('--train_steps', type=int, default=2)
  args = parser.parse_args(argv)
  initialize(args.coordinator, args.num_processes, args.process_id,
             args.local_device_count)
  multihost_dryrun(args.workdir, args.num_processes, args.process_id,
                   args.train_steps)


if __name__ == '__main__':
  main()
