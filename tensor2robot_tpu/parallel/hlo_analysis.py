"""Collective-op accounting from compiled HLO: the scale-out evidence tool.

The reference ships communication as opaque library calls (NCCL/MPI via
TF's distributed runtime); what its graphs actually move per step is
invisible without vendor profilers. Here the communication schedule IS
the compiled program: GSPMD lowers sharding constraints to named HLO
collectives, so the per-step communication volume can be read — and
asserted — straight from the executable. Used by ``__graft_entry__``'s
multichip dryrun (each parallelism family asserts the collectives its
design predicts) and by ``docs/parallelism.md``'s pod-scale projection.

Counting rules:
  * Async pairs (``all-reduce-start``/``-done``) count ONCE, at start.
  * Bytes are the op's RESULT payload (tuple elements summed): for
    all-reduce that equals the reduced tensor size; for all-gather the
    gathered (output) size; for all-to-all the shuffled size;
    reduce-scatter the scattered (smaller) output. This is the
    device-local traffic entering/leaving the op, the quantity an ICI
    bandwidth model consumes; link-level traffic additionally depends on
    the algorithm (ring all-reduce moves ~2x(N-1)/N of the payload).
"""

from __future__ import annotations

import hashlib
import re
from typing import Dict

COLLECTIVE_KINDS = ('all-reduce', 'all-gather', 'all-to-all',
                    'collective-permute', 'reduce-scatter')

_DTYPE_BYTES = {
    'pred': 1, 's8': 1, 'u8': 1, 'f8e4m3fn': 1, 'f8e5m2': 1,
    's16': 2, 'u16': 2, 'f16': 2, 'bf16': 2,
    's32': 4, 'u32': 4, 'f32': 4,
    's64': 8, 'u64': 8, 'f64': 8, 'c64': 8,
    'c128': 16,
}

_SHAPE_RE = re.compile(r'([a-z]+[0-9a-z]*)\[([0-9,]*)\]')
_OP_RE = re.compile(
    r'=\s*(?P<shapes>[^=]*?)\s'
    r'(?P<kind>all-reduce|all-gather|all-to-all|collective-permute|'
    r'reduce-scatter)(?P<variant>-start)?\(')


def _shape_bytes(shapes_str: str) -> int:
  total = 0
  for dtype, dims in _SHAPE_RE.findall(shapes_str):
    size = _DTYPE_BYTES.get(dtype)
    if size is None:
      continue  # token[], opaque[] etc.
    n = 1
    for dim in dims.split(','):
      if dim:
        n *= int(dim)
    total += n * size
  return total


def collective_stats(hlo_text: str) -> Dict[str, Dict[str, int]]:
  """{kind: {'count': n, 'bytes': result_payload_bytes}} from HLO text.

  ``hlo_text``: ``jit(fn).lower(*args).compile().as_text()`` (post-SPMD —
  the collectives only exist after partitioning, so analyze the COMPILED
  module, not the lowered StableHLO).
  """
  stats = {kind: {'count': 0, 'bytes': 0} for kind in COLLECTIVE_KINDS}
  for line in hlo_text.splitlines():
    m = _OP_RE.search(line)
    if not m:
      continue
    kind = m.group('kind')
    nbytes = _shape_bytes(m.group('shapes'))
    if m.group('variant'):
      # Async `-start` ops return an (operands..., results...) tuple —
      # symmetric halves — where the sync lowering returns only the
      # result; halve so the payload is lowering-invariant.
      nbytes //= 2
    stats[kind]['count'] += 1
    stats[kind]['bytes'] += nbytes
  return {k: v for k, v in stats.items() if v['count']}


_INSTR_NAME_RE = re.compile(r'^\s*(?:ROOT\s+)?%(?P<name>[\w.-]+)\s*=')


def collective_ops(hlo_text: str):
  """Per-INSTRUCTION collective index: [{'name', 'kind', 'bytes'}].

  ``collective_stats`` aggregates by kind; this keeps the instruction
  names (``all-reduce.1`` — the same names the profiler's device line
  carries as op events), so forensics can join "which op burned the
  time" (xplane) with "what that op moves" (HLO) and name the gating
  collective of a straggler capture. Async ``-start`` ops keep the
  start name (that is where the device time lands) with the same
  halved-tuple byte rule as ``collective_stats``.
  """
  ops = []
  for line in hlo_text.splitlines():
    m = _OP_RE.search(line)
    if not m:
      continue
    name_match = _INSTR_NAME_RE.match(line)
    nbytes = _shape_bytes(m.group('shapes'))
    if m.group('variant'):
      nbytes //= 2
    ops.append({
        'name': name_match.group('name') if name_match else m.group('kind'),
        'kind': m.group('kind'),
        'bytes': nbytes,
    })
  return ops


def compiled_collective_stats(jitted_fn, *args, **kwargs):
  """Convenience: lower+compile a jitted fn and analyze its collectives."""
  compiled = jitted_fn.lower(*args, **kwargs).compile()
  return collective_stats(compiled.as_text())


def total_collective_bytes(stats: Dict[str, Dict[str, int]]) -> int:
  return sum(v['bytes'] for v in stats.values())


def format_stats(stats: Dict[str, Dict[str, int]]) -> str:
  if not stats:
    return 'no collectives'
  return ', '.join('{}: {}x / {:.2f} MiB'.format(
      kind, v['count'], v['bytes'] / 2**20) for kind, v in stats.items())


# ── Per-op cost model (roofline observatory) ─────────────────────────
#
# Derives FLOPs / HBM bytes per op family from post-optimization HLO
# text — the same artifact every CompiledArtifact persists — so roofline
# attribution works offline, on CPU, and on backends whose
# ``Compiled.cost_analysis()`` is absent or partial. Conventions match
# XLA's HloCostAnalysis so the two sources agree within tolerance:
#
#   * dot          2 x out_elems x contracted_extent
#   * convolution  2 x out_elems x window_elems x in_channels / groups
#   * elementwise  out_elems (one flop per output element)
#   * transcendental (tanh/exp/log/...) counts in a SEPARATE
#     'transcendentals' bucket, NOT flops — mirroring cost_analysis(),
#     whose 'flops' key excludes them.
#   * reduce       in_elems - out_elems
#   * fusion       sum over the called computation's instructions
#   * data movement (copy/reshape/broadcast/...) 0 flops
#
# Bytes are counted for ENTRY-computation instructions only, as
# operand bytes + output bytes (fusion internals live in registers/VMEM
# and never touch HBM); parameter/tuple/get-tuple-element/bitcast are
# free. On the toy matmul+elementwise program this reproduces
# cost_analysis()'s 'bytes accessed' exactly (dot 896 + fusion 256).

_TRANSCENDENTAL_OPS = frozenset((
    'atan2', 'cbrt', 'cosine', 'erf', 'exponential',
    'exponential-minus-one', 'log', 'log-plus-one', 'logistic', 'power',
    'rsqrt', 'sine', 'sqrt', 'tan', 'tanh',
))
_ELEMENTWISE_FLOP_OPS = frozenset((
    'abs', 'add', 'add-dependency', 'and', 'ceil', 'clamp', 'compare',
    'divide', 'floor', 'maximum', 'minimum', 'multiply', 'negate', 'not',
    'or', 'remainder', 'round-nearest-afz', 'round-nearest-even',
    'select', 'shift-left', 'shift-right-arithmetic',
    'shift-right-logical', 'sign', 'subtract', 'xor',
))
_FREE_BYTES_OPS = frozenset((
    'bitcast', 'get-tuple-element', 'parameter', 'tuple',
))

_COMPUTATION_HEADER_RE = re.compile(
    r'^\s*(?P<entry>ENTRY\s+)?%?(?P<name>[\w.-]+)\s*\([^)]*\)\s*->')
_OPCODE_RE = re.compile(r'(?P<opcode>[a-z][a-z0-9-]*)\(')
_CALLS_RE = re.compile(r'(?:calls|to_apply)=%?(?P<name>[\w.-]+)')
_CONTRACTING_RE = re.compile(r'lhs_contracting_dims=\{(?P<dims>[0-9,]*)\}')
_WINDOW_SIZE_RE = re.compile(r'window=\{[^}]*size=(?P<size>[0-9x]+)')
_DIM_LABELS_RE = re.compile(r'dim_labels=(?P<lhs>[\w?]+)_[\w?]+->')
_GROUPS_RE = re.compile(r'feature_group_count=(?P<n>\d+)')
_FAMILY_SUFFIX_RE = re.compile(r'\.\d+$')


def _shape_elems(shapes_str: str) -> int:
  total = 0
  for _, dims in _SHAPE_RE.findall(shapes_str):
    n = 1
    for dim in dims.split(','):
      if dim:
        n *= int(dim)
    total += n
  return total


def _shape_dims(shape_str: str):
  m = _SHAPE_RE.search(shape_str)
  if not m:
    return []
  return [int(d) for d in m.group(2).split(',') if d]


def _split_instruction(line: str):
  """(name, opcode, out_str, operand_str, attrs_str) or None."""
  m = _INSTR_NAME_RE.match(line)
  if not m:
    return None
  rest = line.split('=', 1)[1]
  op = _OPCODE_RE.search(rest)
  if not op:
    return None
  out_str = rest[:op.start()]
  depth = 0
  start = op.end() - 1
  end = len(rest)
  for i in range(start, len(rest)):
    if rest[i] == '(':
      depth += 1
    elif rest[i] == ')':
      depth -= 1
      if depth == 0:
        end = i
        break
  return (m.group('name'), op.group('opcode'), out_str,
          rest[start + 1:end], rest[end + 1:])


def _parse_computations(hlo_text: str):
  """{computation_name: [instruction tuples]}, plus the ENTRY name."""
  computations: Dict[str, list] = {}
  entry_name = None
  current = None
  for line in hlo_text.splitlines():
    stripped = line.strip()
    if current is None:
      if stripped.endswith('{'):
        header = _COMPUTATION_HEADER_RE.match(stripped)
        if header:
          current = header.group('name')
          computations[current] = []
          if header.group('entry'):
            entry_name = current
      continue
    if stripped.startswith('}'):
      current = None
      continue
    instr = _split_instruction(line)
    if instr:
      computations[current].append(instr)
  return computations, entry_name


def _instr_flops(instr, computations, memo):
  """(flops, transcendentals) for one parsed instruction."""
  _, opcode, out_str, operand_str, attrs = instr
  out_elems = _shape_elems(out_str)
  if opcode == 'dot':
    lhs_dims = _shape_dims(operand_str)
    contracted = 1
    m = _CONTRACTING_RE.search(attrs)
    if m and lhs_dims:
      for d in m.group('dims').split(','):
        if d and int(d) < len(lhs_dims):
          contracted *= lhs_dims[int(d)]
    return 2 * out_elems * contracted, 0
  if opcode == 'convolution':
    window = 1
    m = _WINDOW_SIZE_RE.search(attrs)
    if m:
      for s in m.group('size').split('x'):
        window *= int(s)
    in_channels = 1
    labels = _DIM_LABELS_RE.search(attrs)
    lhs_dims = _shape_dims(operand_str)
    if labels and 'f' in labels.group('lhs'):
      idx = labels.group('lhs').index('f')
      if idx < len(lhs_dims):
        in_channels = lhs_dims[idx]
    groups = 1
    m = _GROUPS_RE.search(attrs)
    if m:
      groups = max(int(m.group('n')), 1)
    return 2 * out_elems * window * in_channels // groups, 0
  if opcode == 'fusion':
    m = _CALLS_RE.search(attrs)
    if m:
      return _computation_flops(m.group('name'), computations, memo)
    return 0, 0
  if opcode in ('reduce', 'reduce-window'):
    in_elems = _shape_elems(operand_str)
    return max(in_elems - out_elems, 0), 0
  if opcode in _ELEMENTWISE_FLOP_OPS:
    return out_elems, 0
  if opcode in _TRANSCENDENTAL_OPS:
    return 0, out_elems
  return 0, 0


def _computation_flops(name, computations, memo):
  if name in memo:
    return memo[name]
  memo[name] = (0, 0)  # cycle guard
  flops = transcendentals = 0
  for instr in computations.get(name, ()):
    f, t = _instr_flops(instr, computations, memo)
    flops += f
    transcendentals += t
  memo[name] = (flops, transcendentals)
  return memo[name]


def op_cost_table(hlo_text: str) -> Dict[str, Dict[str, float]]:
  """{op family: {'flops', 'bytes', 'transcendentals', 'count'}}.

  Families carry the same naming as ``utils/xplane.op_families`` device
  events — ``'%' + instruction name with the trailing .N stripped`` — so
  a forensics capture's measured ms joins this table directly. Only the
  ENTRY computation's instructions appear (those are the ops the device
  line times); fusions fold their called computation's flops into the
  fusion family.
  """
  computations, entry = _parse_computations(hlo_text)
  if entry is None:
    return {}
  memo: Dict[str, tuple] = {}
  table: Dict[str, Dict[str, float]] = {}
  for instr in computations[entry]:
    name, opcode, out_str, operand_str, _ = instr
    flops, transcendentals = _instr_flops(instr, computations, memo)
    nbytes = 0
    if opcode not in _FREE_BYTES_OPS:
      nbytes = _shape_bytes(out_str) + _shape_bytes(operand_str)
    family = '%' + _FAMILY_SUFFIX_RE.sub('', name)
    row = table.setdefault(family, {
        'flops': 0.0, 'bytes': 0.0, 'transcendentals': 0.0, 'count': 0})
    row['flops'] += flops
    row['bytes'] += nbytes
    row['transcendentals'] += transcendentals
    row['count'] += 1
  return table


def hlo_program_cost(hlo_text: str) -> Dict[str, float]:
  """Program totals from HLO text: {'flops', 'bytes', 'transcendentals'}."""
  totals = {'flops': 0.0, 'bytes': 0.0, 'transcendentals': 0.0}
  for row in op_cost_table(hlo_text).values():
    totals['flops'] += row['flops']
    totals['bytes'] += row['bytes']
    totals['transcendentals'] += row['transcendentals']
  return totals


def program_cost(compiled_or_text) -> Dict[str, object]:
  """THE shared FLOPs/bytes accounting helper (bench, trainer, roofline).

  Accepts a compiled executable or its ``as_text()`` string. Prefers the
  backend's own ``cost_analysis()`` (exact, fusion-aware); falls back to
  the HLO shape parse above when the method is missing, raises, or
  reports non-positive flops (some backends return properties without
  compute counts). Returns ``{'flops', 'bytes', 'transcendentals',
  'source'}`` with source in ('cost_analysis', 'hlo_parse') so callers
  can surface which model produced the number.
  """
  text = compiled_or_text if isinstance(compiled_or_text, str) else None
  if text is None:
    try:
      props = compiled_or_text.cost_analysis()
      if isinstance(props, (list, tuple)):
        props = props[0]
      flops = float(props.get('flops', -1.0))
      nbytes = float(props.get('bytes accessed', -1.0))
      if flops > 0 and nbytes > 0:
        return {
            'flops': flops,
            'bytes': nbytes,
            'transcendentals': float(props.get('transcendentals', 0.0)),
            'source': 'cost_analysis',
        }
    except Exception:  # noqa: BLE001 - fall through to the HLO parse
      pass
    text = compiled_or_text.as_text()
  totals = hlo_program_cost(text)
  totals['source'] = 'hlo_parse'
  return totals


_MODULE_HEADER_RE = re.compile(r'^HloModule\s+\S+', re.MULTILINE)


def program_fingerprint(compiled_or_text) -> str:
  """Short stable sha1 of a compiled program's post-optimization HLO.

  Accepts a compiled executable (``jit(f).lower(...).compile()``) or its
  ``as_text()`` string. Comment lines and the HloModule header (which
  carries a per-compile module id) are stripped so the digest depends
  only on the optimized program itself. The compile-config autotuner
  records this per candidate: two candidates with the SAME fingerprint
  compiled to the SAME program, so their timing delta is noise and the
  flag was a no-op for this workload — measured, not assumed.
  """
  text = compiled_or_text
  if not isinstance(text, str):
    text = compiled_or_text.as_text()
  lines = [line.strip() for line in text.splitlines()
           if line.strip() and not line.strip().startswith('//')]
  body = _MODULE_HEADER_RE.sub('HloModule <normalized>', '\n'.join(lines))
  return hashlib.sha1(body.encode('utf-8')).hexdigest()[:16]
