"""Collective-op accounting from compiled HLO: the scale-out evidence tool.

The reference ships communication as opaque library calls (NCCL/MPI via
TF's distributed runtime); what its graphs actually move per step is
invisible without vendor profilers. Here the communication schedule IS
the compiled program: GSPMD lowers sharding constraints to named HLO
collectives, so the per-step communication volume can be read — and
asserted — straight from the executable. Used by ``__graft_entry__``'s
multichip dryrun (each parallelism family asserts the collectives its
design predicts) and by ``docs/parallelism.md``'s pod-scale projection.

Counting rules:
  * Async pairs (``all-reduce-start``/``-done``) count ONCE, at start.
  * Bytes are the op's RESULT payload (tuple elements summed): for
    all-reduce that equals the reduced tensor size; for all-gather the
    gathered (output) size; for all-to-all the shuffled size;
    reduce-scatter the scattered (smaller) output. This is the
    device-local traffic entering/leaving the op, the quantity an ICI
    bandwidth model consumes; link-level traffic additionally depends on
    the algorithm (ring all-reduce moves ~2x(N-1)/N of the payload).
"""

from __future__ import annotations

import hashlib
import re
from typing import Dict

COLLECTIVE_KINDS = ('all-reduce', 'all-gather', 'all-to-all',
                    'collective-permute', 'reduce-scatter')

_DTYPE_BYTES = {
    'pred': 1, 's8': 1, 'u8': 1, 'f8e4m3fn': 1, 'f8e5m2': 1,
    's16': 2, 'u16': 2, 'f16': 2, 'bf16': 2,
    's32': 4, 'u32': 4, 'f32': 4,
    's64': 8, 'u64': 8, 'f64': 8, 'c64': 8,
    'c128': 16,
}

_SHAPE_RE = re.compile(r'([a-z]+[0-9a-z]*)\[([0-9,]*)\]')
_OP_RE = re.compile(
    r'=\s*(?P<shapes>[^=]*?)\s'
    r'(?P<kind>all-reduce|all-gather|all-to-all|collective-permute|'
    r'reduce-scatter)(?P<variant>-start)?\(')


def _shape_bytes(shapes_str: str) -> int:
  total = 0
  for dtype, dims in _SHAPE_RE.findall(shapes_str):
    size = _DTYPE_BYTES.get(dtype)
    if size is None:
      continue  # token[], opaque[] etc.
    n = 1
    for dim in dims.split(','):
      if dim:
        n *= int(dim)
    total += n * size
  return total


def collective_stats(hlo_text: str) -> Dict[str, Dict[str, int]]:
  """{kind: {'count': n, 'bytes': result_payload_bytes}} from HLO text.

  ``hlo_text``: ``jit(fn).lower(*args).compile().as_text()`` (post-SPMD —
  the collectives only exist after partitioning, so analyze the COMPILED
  module, not the lowered StableHLO).
  """
  stats = {kind: {'count': 0, 'bytes': 0} for kind in COLLECTIVE_KINDS}
  for line in hlo_text.splitlines():
    m = _OP_RE.search(line)
    if not m:
      continue
    kind = m.group('kind')
    nbytes = _shape_bytes(m.group('shapes'))
    if m.group('variant'):
      # Async `-start` ops return an (operands..., results...) tuple —
      # symmetric halves — where the sync lowering returns only the
      # result; halve so the payload is lowering-invariant.
      nbytes //= 2
    stats[kind]['count'] += 1
    stats[kind]['bytes'] += nbytes
  return {k: v for k, v in stats.items() if v['count']}


_INSTR_NAME_RE = re.compile(r'^\s*(?:ROOT\s+)?%(?P<name>[\w.-]+)\s*=')


def collective_ops(hlo_text: str):
  """Per-INSTRUCTION collective index: [{'name', 'kind', 'bytes'}].

  ``collective_stats`` aggregates by kind; this keeps the instruction
  names (``all-reduce.1`` — the same names the profiler's device line
  carries as op events), so forensics can join "which op burned the
  time" (xplane) with "what that op moves" (HLO) and name the gating
  collective of a straggler capture. Async ``-start`` ops keep the
  start name (that is where the device time lands) with the same
  halved-tuple byte rule as ``collective_stats``.
  """
  ops = []
  for line in hlo_text.splitlines():
    m = _OP_RE.search(line)
    if not m:
      continue
    name_match = _INSTR_NAME_RE.match(line)
    nbytes = _shape_bytes(m.group('shapes'))
    if m.group('variant'):
      nbytes //= 2
    ops.append({
        'name': name_match.group('name') if name_match else m.group('kind'),
        'kind': m.group('kind'),
        'bytes': nbytes,
    })
  return ops


def compiled_collective_stats(jitted_fn, *args, **kwargs):
  """Convenience: lower+compile a jitted fn and analyze its collectives."""
  compiled = jitted_fn.lower(*args, **kwargs).compile()
  return collective_stats(compiled.as_text())


def total_collective_bytes(stats: Dict[str, Dict[str, int]]) -> int:
  return sum(v['bytes'] for v in stats.values())


def format_stats(stats: Dict[str, Dict[str, int]]) -> str:
  if not stats:
    return 'no collectives'
  return ', '.join('{}: {}x / {:.2f} MiB'.format(
      kind, v['count'], v['bytes'] / 2**20) for kind, v in stats.items())


_MODULE_HEADER_RE = re.compile(r'^HloModule\s+\S+', re.MULTILINE)


def program_fingerprint(compiled_or_text) -> str:
  """Short stable sha1 of a compiled program's post-optimization HLO.

  Accepts a compiled executable (``jit(f).lower(...).compile()``) or its
  ``as_text()`` string. Comment lines and the HloModule header (which
  carries a per-compile module id) are stripped so the digest depends
  only on the optimized program itself. The compile-config autotuner
  records this per candidate: two candidates with the SAME fingerprint
  compiled to the SAME program, so their timing delta is noise and the
  flag was a no-op for this workload — measured, not assumed.
  """
  text = compiled_or_text
  if not isinstance(text, str):
    text = compiled_or_text.as_text()
  lines = [line.strip() for line in text.splitlines()
           if line.strip() and not line.strip().startswith('//')]
  body = _MODULE_HEADER_RE.sub('HloModule <normalized>', '\n'.join(lines))
  return hashlib.sha1(body.encode('utf-8')).hexdigest()[:16]
