"""Pallas flash attention: blockwise online-softmax attention in VMEM.

The long-context compute kernel (SURVEY §7 Pallas candidates; the ring
layer in parallel/ring_attention.py handles the multi-device dimension).
XLA's attention materializes the full [B, H, L, L] score tensor in HBM —
O(L^2) memory and two full HBM round-trips over it. This kernel tiles
q into [block_q, D] VMEM blocks and streams k/v through in [block_k, D]
blocks, keeping the running (max, sum, accumulator) of the numerically
stable online softmax (Milakov & Gimelshein 2018; Dao et al. 2022,
FlashAttention) in VMEM scratch that persists across the innermost grid
dimension:

  grid = (batch*heads, n_q_tiles, L/block_k, tile/block_q)
         # k OUTER within a q tile, q INNER: each k/v block is fetched
         # once per k step and reused by the whole tile's q sweep (the
         # FlashAttention-2 loop order); the tile's accumulators stay
         # resident in VMEM scratch. Fully-masked causal blocks skip.
  s    = q_block @ k_block^T * scale           # MXU, f32 accumulation
  m'   = max(m, rowmax(s));  p = exp(s - m')   # VPU
  l    = l * exp(m - m') + rowsum(p)
  acc  = acc * exp(m - m') + p @ v_block       # MXU
  at the last k block: out = acc / l

Memory: per-device O(L*D) activations only — no score tensor ever reaches
HBM. Numerics match the XLA oracle to f32 rounding
(tests/test_flash_attention.py); measured speed/memory comparison in
docs/performance.md (~4-5x over XLA attention at 16k tokens; runs 32k
where XLA OOMs). This is the single-device long-context path;
ring_attention.py handles the cross-device dimension with its own
shard-level blockwise accumulation.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _block_update(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, *,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  q_offset, k_offset, i_q, i_k):
  """The shared online-softmax block update both kernels run.

  Reads one q/k/v block from refs, scores it, and folds it into the
  (acc, m, l) scratch accumulators. ``q_offset``/``k_offset`` are the
  GLOBAL positions of the blocks' first rows (plain ints or traced
  scalars) for causal masking. ``i_q``/``i_k`` are the grid indices,
  passed in because pl.program_id cannot be called inside a pl.when
  branch under the CPU interpreter.
  """
  q = q_ref[0].astype(jnp.float32)                       # [bq, D]
  k = k_ref[0].astype(jnp.float32)                       # [bk, D]
  v = v_ref[0].astype(jnp.float32)
  s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32) * scale
  if causal:
    q_pos = (q_offset + i_q * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0))
    k_pos = (k_offset + i_k * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1))
    s = jnp.where(q_pos >= k_pos, s, NEG_INF)

  m_prev = m_ref[:]                                      # [bq, 1]
  l_prev = l_ref[:]
  m_block = jnp.max(s, axis=-1, keepdims=True)           # [bq, 1]
  m_new = jnp.maximum(m_prev, m_block)
  safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
  p = jnp.exp(s - safe_m)
  p = jnp.where(s <= NEG_INF / 2, 0.0, p)
  correction = jnp.exp(m_prev - safe_m)
  correction = jnp.where(m_prev <= NEG_INF / 2, 0.0, correction)
  l_ref[:] = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
  m_ref[:] = m_new
  acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
      p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                  l_ref, *, scale: float, causal: bool, block_q: int,
                  block_k: int):
  """One step of the k-outer / q-inner sweep within a q TILE.

  The grid is (bh, n_q_outer, n_k, n_q_inner): within one q tile
  (n_q_inner * block_q rows, accumulators resident in VMEM scratch),
  k is the outer loop — so Pallas fetches each k/v block ONCE per k
  step and the inner q sweep reuses it from VMEM. With q fully outer
  (the FlashAttention-1 order) every k/v block is re-fetched for every
  q block; at long L the kernel was bound by those copies, not the MXU
  (measured 12.7 ms at L=16k vs ~4 ms in this order). The q tile keeps
  scratch under the 16 MB scoped-VMEM limit; k/v blocks are re-fetched
  only once per TILE (L/tile times total).
  """
  i_qo = pl.program_id(1)
  i_k = pl.program_id(2)
  i_qi = pl.program_id(3)
  n_k = pl.num_programs(2)
  n_qi = pl.num_programs(3)
  i_q = i_qo * n_qi + i_qi            # global q-block index
  rows = pl.dslice(i_qi * block_q, block_q)

  @pl.when(i_k == 0)
  def _init():
    acc_ref[rows, :] = jnp.zeros((block_q, acc_ref.shape[-1]), jnp.float32)
    m_ref[rows, :] = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l_ref[rows, :] = jnp.zeros((block_q, 1), jnp.float32)

  def _do_update():
    # One shared numerics implementation (_block_update) for both this
    # kernel and the ring-carry kernel; the tile's accumulator rows are
    # exposed as sub-refs.
    _block_update(q_ref, k_ref, v_ref, acc_ref.at[rows, :],
                  m_ref.at[rows, :], l_ref.at[rows, :], scale=scale,
                  causal=causal, block_q=block_q, block_k=block_k,
                  q_offset=0, k_offset=0, i_q=i_q, i_k=i_k)

  if causal:
    # Skip blocks entirely above the causal diagonal (all scores -inf).
    @pl.when(i_q * block_q + block_q - 1 >= i_k * block_k)
    def _update():
      _do_update()
  else:
    _do_update()

  @pl.when(i_k == n_k - 1)
  def _finalize():
    l_final = jnp.maximum(l_ref[rows, :], 1e-20)
    o_ref[0] = (acc_ref[rows, :] / l_final).astype(o_ref.dtype)
    # Log-sum-exp per row, saved for the backward pass (FlashAttention).
    # Broadcast over the 8 padding sublanes (see _flash_bhld's lse shape).
    row = (m_ref[rows, :] + jnp.log(l_final))[:, 0]
    lse_ref[0] = jnp.broadcast_to(row[None, :], (8, block_q))


def _flash_bhld(q, k, v, *, scale: float, causal: bool, block_q: int,
                block_k: int, interpret: bool):
  """[BH, L, D] flash attention via pallas_call.

  The log-sum-exp output is materialized as [BH, 8, L] — Mosaic requires
  output blocks whose second-minor dim is divisible by 8 (or equals the
  array dim), so the per-row LSE is broadcast over 8 padding sublanes in
  the kernel and sliced back to [BH, L] here. The waste is 7 f32 rows per
  (bh, L): ~3.5 MB at bh=8, L=16k — noise next to the k/v tensors.
  """
  bh, l_q, d = q.shape
  l_k = k.shape[1]
  n_q = pl.cdiv(l_q, block_q)
  n_k = pl.cdiv(l_k, block_k)
  # q rows per tile: as many q blocks as fit a few MB of f32 accumulator
  # scratch AND divide n_q evenly (grid dims are rectangular).
  max_qi = max(1, (4096 // block_q))
  n_qi = max_qi
  while n_q % n_qi:
    n_qi -= 1
  n_qo = n_q // n_qi
  tile_rows = n_qi * block_q
  kernel = functools.partial(
      _flash_kernel, scale=scale, causal=causal, block_q=block_q,
      block_k=block_k)
  # Grid: per q TILE, k OUTER / q INNER (see _flash_kernel) — each k/v
  # block is fetched once per k step per tile; the tile's accumulators
  # live in VMEM scratch.
  out, lse8 = pl.pallas_call(
      kernel,
      grid=(bh, n_qo, n_k, n_qi),
      in_specs=[
          pl.BlockSpec((1, block_q, d),
                       lambda b, qo, j, qi, n=n_qi: (b, qo * n + qi, 0)),
          pl.BlockSpec((1, block_k, d), lambda b, qo, j, qi: (b, j, 0)),
          pl.BlockSpec((1, block_k, d), lambda b, qo, j, qi: (b, j, 0)),
      ],
      out_specs=[
          pl.BlockSpec((1, block_q, d),
                       lambda b, qo, j, qi, n=n_qi: (b, qo * n + qi, 0)),
          pl.BlockSpec((1, 8, block_q),
                       lambda b, qo, j, qi, n=n_qi: (b, 0, qo * n + qi)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct(q.shape, q.dtype),
          jax.ShapeDtypeStruct((bh, 8, l_q), jnp.float32),
      ],
      scratch_shapes=[
          pltpu.VMEM((tile_rows, d), jnp.float32),
          pltpu.VMEM((tile_rows, 1), jnp.float32),
          pltpu.VMEM((tile_rows, 1), jnp.float32),
      ],
      interpret=interpret,
  )(q, k, v)
  return out, lse8[:, 0, :]


def _flash_carry_kernel(offsets_ref, q_ref, k_ref, v_ref, o_in_ref,
                        m_in_ref, l_in_ref, o_out_ref, m_out_ref,
                        l_out_ref, acc_ref, m_ref, l_ref, *, scale: float,
                        causal: bool, block_q: int, block_k: int):
  """Flash block update with EXTERNAL accumulators (for ring attention).

  Like _flash_kernel but the online-softmax state (o, m, l) is carried in
  and out UNNORMALIZED — the ring loop feeds each hop's outputs into the
  next and normalizes once at the end. ``offsets_ref`` (scalar prefetch)
  holds the global (q_offset, k_offset) so causal masking sees global
  positions even though each device only holds its shard.
  """
  i_q = pl.program_id(1)
  i_k = pl.program_id(2)
  n_k = pl.num_programs(2)

  @pl.when(i_k == 0)
  def _init():
    acc_ref[:] = o_in_ref[0].astype(jnp.float32)
    # m/l ride in [1, 8, block_q] blocks (8 broadcast sublanes — Mosaic's
    # output-block divisibility rule; see _flash_bhld's lse note).
    m_ref[:] = m_in_ref[0, 0].astype(jnp.float32)[:, None]
    l_ref[:] = l_in_ref[0, 0].astype(jnp.float32)[:, None]

  def _do_update():
    _block_update(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, scale=scale,
                  causal=causal, block_q=block_q, block_k=block_k,
                  q_offset=offsets_ref[0], k_offset=offsets_ref[1],
                  i_q=i_q, i_k=i_k)

  if causal:
    # Global-position block skip (offsets are traced scalars): the block
    # contributes nothing when its largest q position is left of its
    # smallest k position.
    @pl.when(offsets_ref[0] + i_q * block_q + block_q - 1
             >= offsets_ref[1] + i_k * block_k)
    def _update():
      _do_update()
  else:
    _do_update()

  @pl.when(i_k == n_k - 1)
  def _finalize():
    o_out_ref[0] = acc_ref[:]
    m_out_ref[0] = jnp.broadcast_to(m_ref[:][:, 0][None, :],
                                    (8, block_q))
    l_out_ref[0] = jnp.broadcast_to(l_ref[:][:, 0][None, :],
                                    (8, block_q))


def flash_attention_carry(q, k, v, o, m, l, q_offset, k_offset,
                          causal: bool, scale: float,
                          block_q: int = 128, block_k: int = 128,
                          interpret: Optional[bool] = None):
  """One unnormalized flash update of (o, m, l) with a new k/v block.

  Shapes: q [BH, Lq, D]; k/v [BH, Lk, D]; o [BH, Lq, D] f32; m/l [BH, Lq]
  f32. ``q_offset``/``k_offset`` are traced global-position scalars.
  Returns updated (o, m, l). This is the ring-attention inner kernel;
  forward-only (no VJP) — the differentiable ring path is the jnp one.
  """
  if interpret is None:
    interpret = jax.default_backend() == 'cpu'
  bh, l_q, d = q.shape
  l_k = k.shape[1]
  block_q = min(block_q, l_q)
  block_k = min(block_k, l_k)
  if l_q % block_q or l_k % block_k:
    raise ValueError(
        'Shard lengths ({}, {}) must be multiples of the block sizes '
        '({}, {}).'.format(l_q, l_k, block_q, block_k))
  n_q = l_q // block_q
  n_k = l_k // block_k
  kernel = functools.partial(
      _flash_carry_kernel, scale=scale, causal=causal, block_q=block_q,
      block_k=block_k)
  offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                       jnp.asarray(k_offset, jnp.int32)])
  # m/l carries are padded to 8 broadcast sublanes for Mosaic's block
  # divisibility rule (same scheme as _flash_bhld's lse output).
  m8 = jnp.broadcast_to(m[:, None, :], (bh, 8, l_q))
  l8 = jnp.broadcast_to(l[:, None, :], (bh, 8, l_q))
  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=1,
      grid=(bh, n_q, n_k),
      # Index maps receive the scalar-prefetch ref as a trailing arg.
      in_specs=[
          pl.BlockSpec((1, block_q, d), lambda b, i, j, off: (b, i, 0)),
          pl.BlockSpec((1, block_k, d), lambda b, i, j, off: (b, j, 0)),
          pl.BlockSpec((1, block_k, d), lambda b, i, j, off: (b, j, 0)),
          pl.BlockSpec((1, block_q, d), lambda b, i, j, off: (b, i, 0)),
          pl.BlockSpec((1, 8, block_q), lambda b, i, j, off: (b, 0, i)),
          pl.BlockSpec((1, 8, block_q), lambda b, i, j, off: (b, 0, i)),
      ],
      out_specs=[
          pl.BlockSpec((1, block_q, d), lambda b, i, j, off: (b, i, 0)),
          pl.BlockSpec((1, 8, block_q), lambda b, i, j, off: (b, 0, i)),
          pl.BlockSpec((1, 8, block_q), lambda b, i, j, off: (b, 0, i)),
      ],
      scratch_shapes=[
          pltpu.VMEM((block_q, d), jnp.float32),
          pltpu.VMEM((block_q, 1), jnp.float32),
          pltpu.VMEM((block_q, 1), jnp.float32),
      ],
  )
  o_out, m_out8, l_out8 = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=[
          jax.ShapeDtypeStruct(o.shape, jnp.float32),
          jax.ShapeDtypeStruct((bh, 8, l_q), jnp.float32),
          jax.ShapeDtypeStruct((bh, 8, l_q), jnp.float32),
      ],
      interpret=interpret,
  )(offsets, q, k, v, o, m8, l8)
  return o_out, m_out8[:, 0, :], l_out8[:, 0, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_diff(q, k, v, causal, scale, block_q, block_k, interpret):
  """custom_vjp core over [BH, L, D] operands."""
  out, _ = _flash_bhld(q, k, v, scale=scale, causal=causal,
                       block_q=block_q, block_k=block_k,
                       interpret=interpret)
  return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
  out, lse = _flash_bhld(q, k, v, scale=scale, causal=causal,
                         block_q=block_q, block_k=block_k,
                         interpret=interpret)
  return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, residuals, d_out):
  """Blockwise FlashAttention backward: a scan over k/v blocks.

  Recomputes P per block from the saved log-sum-exp; memory stays
  O(L * block_k) — the [L, L] score tensor is never materialized. XLA
  compiles the scan body (it is matmul-dominated, so the MXU sees the
  same shapes as the forward kernel).
  """
  del block_q
  q, k, v, out, lse = residuals
  bh, l_q, d = q.shape
  l_k = k.shape[1]
  n_k = l_k // block_k
  qf = q.astype(jnp.float32)
  do = d_out.astype(jnp.float32)
  delta = jnp.sum(do * out.astype(jnp.float32), axis=-1)       # [BH, Lq]
  k_blocks = k.astype(jnp.float32).reshape(bh, n_k, block_k, d)
  v_blocks = v.astype(jnp.float32).reshape(bh, n_k, block_k, d)
  q_pos = jnp.arange(l_q)

  def body(dq_acc, inputs):
    j, k_j, v_j = inputs                                       # [BH, bk, D]
    s = jnp.einsum('bqd,bkd->bqk', qf, k_j) * scale            # [BH, Lq, bk]
    if causal:
      k_pos = j * block_k + jnp.arange(block_k)
      s = jnp.where(q_pos[None, :, None] >= k_pos[None, None, :], s,
                    NEG_INF)
    p = jnp.exp(s - lse[:, :, None])
    dv_j = jnp.einsum('bqk,bqd->bkd', p, do)
    dp = jnp.einsum('bqd,bkd->bqk', do, v_j)
    ds = p * (dp - delta[:, :, None]) * scale
    dk_j = jnp.einsum('bqk,bqd->bkd', ds, qf)
    dq_acc = dq_acc + jnp.einsum('bqk,bkd->bqd', ds, k_j)
    return dq_acc, (dk_j, dv_j)

  dq, (dk_blocks, dv_blocks) = jax.lax.scan(
      body, jnp.zeros(q.shape, jnp.float32),
      (jnp.arange(n_k), k_blocks.transpose(1, 0, 2, 3),
       v_blocks.transpose(1, 0, 2, 3)))
  dk = dk_blocks.transpose(1, 0, 2, 3).reshape(k.shape)
  dv = dv_blocks.transpose(1, 0, 2, 3).reshape(v.shape)
  return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_diff.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 1024,
                    block_k: int = 1024,
                    interpret: Optional[bool] = None):
  """Exact attention over [B, L, H, D] inputs, O(L) memory, differentiable.

  Forward runs the Pallas kernel (k-outer/q-inner tiled sweep, see
  _flash_kernel); the backward is the blockwise FlashAttention
  recomputation (custom VJP) so training never sees an [L, L] tensor
  either. Blocks step down automatically to sizes dividing L.
  ``interpret=None`` auto-selects the Pallas interpreter off-TPU so
  tests run on CPU.

  Default block sizes come from v5e sweeps (B=1, H=8, D=128, causal,
  chained on-device timing): (1024, 1024) measures 5.0/6.2/~9/25.5 ms at
  L=4k/8k/16k/32k — grid-step count (fixed per-step overhead) and k/v
  re-fetch traffic are the levers, so bigger blocks win until the
  f32 score matrix presses the 16 MB scoped-VMEM limit.
  """
  if scale is None:
    scale = 1.0 / float(np.sqrt(q.shape[-1]))
  if interpret is None:
    interpret = jax.default_backend() == 'cpu'
  b, l_q, h, d = q.shape
  l_k = k.shape[1]
  if jnp.dtype(q.dtype).itemsize >= 4:
    # f32 operands double the VMEM block footprint; the bf16-tuned
    # (1024, 1024) defaults press past the 16 MB scoped-VMEM limit at
    # L>=4096 (measured: 'Scoped allocation ... exceeded scoped vmem
    # limit'). Conservative caps keep the f32 working set a few MB.
    block_q = min(block_q, 256)
    block_k = min(block_k, 512)

  def _dividing_block(requested, l):
    """Largest block <= requested that divides L (stepping down through
    the power-of-two ladder), so any L works at reduced block efficiency
    instead of raising."""
    for candidate in (requested, 512, 256, 128, 64, 32, 16, 8):
      if candidate <= l and l % candidate == 0 and candidate <= requested:
        return candidate
    return l

  block_q = _dividing_block(min(block_q, l_q), l_q)
  block_k = _dividing_block(min(block_k, l_k), l_k)
  if l_q % block_q or l_k % block_k:  # unreachable: l divides l
    raise ValueError(
        'Sequence lengths ({}, {}) must be multiples of the block sizes '
        '({}, {}).'.format(l_q, l_k, block_q, block_k))

  def _to_bhld(x):
    return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

  out = _flash_diff(_to_bhld(q), _to_bhld(k), _to_bhld(v), causal, scale,
                    block_q, block_k, interpret)
  return out.reshape(b, h, l_q, d).transpose(0, 2, 1, 3)
