"""Pallas flash attention: blockwise online-softmax attention in VMEM.

The long-context compute kernel (SURVEY §7 Pallas candidates; the ring
layer in parallel/ring_attention.py handles the multi-device dimension).
XLA's attention materializes the full [B, H, L, L] score tensor in HBM —
O(L^2) memory and two full HBM round-trips over it. This kernel tiles
q into [block_q, D] VMEM blocks and streams k/v through in [block_k, D]
blocks, keeping the running (max, sum, accumulator) of the numerically
stable online softmax (Milakov & Gimelshein 2018; Dao et al. 2022,
FlashAttention) in VMEM scratch that persists across the innermost grid
dimension:

  grid = (batch*heads, n_q_tiles, L/block_k, tile/block_q)
         # k OUTER within a q tile, q INNER: each k/v block is fetched
         # once per k step and reused by the whole tile's q sweep (the
         # FlashAttention-2 loop order); the tile's accumulators stay
         # resident in VMEM scratch. Fully-masked causal blocks skip.
  s    = q_block @ k_block^T * scale           # MXU, f32 accumulation
  m'   = max(m, rowmax(s));  p = exp(s - m')   # VPU
  l    = l * exp(m - m') + rowsum(p)
  acc  = acc * exp(m - m') + p @ v_block       # MXU
  at the last k block: out = acc / l

Memory: per-device O(L*D) activations only — no score tensor ever reaches
HBM, forward OR backward: since round 4 the backward is the same kernel
family (two Pallas kernels, FlashAttention-2 structure, causal block
skip — _flash_bwd_pallas) instead of an XLA scan. Numerics match the XLA
oracle to f32 rounding (tests/test_flash_attention.py); measured numbers
in docs/performance.md (B=1 H=8 D=128 causal, jax 0.9: fwd 7.7/12.1/29.6
ms at L=4k/16k/32k vs XLA 13.1/46.4/OOM; fwd+bwd 10.8/18.1/60.4 ms vs
XLA 11.3/uncompilable/uncompilable). This is the single-device
long-context path; ring_attention.py handles the cross-device dimension
with its own shard-level blockwise accumulation.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _dividing_block_or_raise(requested: int, l: int) -> int:
  """Largest block <= requested that divides L (power-of-two ladder).

  Raises for lengths nothing on the ladder divides (L % 8 != 0) instead
  of silently returning L itself — a full-length "block" bypasses the
  VMEM sizing the caps encode and surfaces later as an opaque Mosaic
  scoped-vmem error. Callers pad the sequence instead.
  """
  for candidate in (requested, 512, 256, 128, 64, 32, 16, 8):
    if (candidate % 8 == 0 and candidate <= l and l % candidate == 0
        and candidate <= requested):
      # candidate % 8: requested itself heads the ladder, and for L <=
      # requested that first candidate is L — an 8-misaligned L must fall
      # through to the raise, not return itself as a full-length "block".
      return candidate
  raise ValueError(
      'No flash-attention block size <= {} divides sequence length {}; '
      'pad the sequence to a multiple of 8.'.format(requested, l))


def _block_update(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, *,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  q_offset, k_offset, i_q, i_k):
  """The shared online-softmax block update both kernels run.

  Reads one q/k/v block from refs, scores it, and folds it into the
  (acc, m, l) scratch accumulators. ``q_offset``/``k_offset`` are the
  GLOBAL positions of the blocks' first rows (plain ints or traced
  scalars) for causal masking. ``i_q``/``i_k`` are the grid indices,
  passed in because pl.program_id cannot be called inside a pl.when
  branch under the CPU interpreter.

  m/l scratch is [bq, 128] with the per-row scalar broadcast UNIFORMLY
  across all 128 lanes: jax 0.9's Mosaic rejects sub-slicing width-1
  VMEM memrefs ("slice shape along dimension 1 must be aligned to
  tiling (128)"), so the scalars are read back with a lane-reduce and
  stored with a broadcast instead of living in [bq, 1] refs.
  """
  q = q_ref[0].astype(jnp.float32)                       # [bq, D]
  k = k_ref[0].astype(jnp.float32)                       # [bk, D]
  v = v_ref[0].astype(jnp.float32)
  s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32) * scale
  if causal:
    q_pos = (q_offset + i_q * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0))
    k_pos = (k_offset + i_k * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1))
    s = jnp.where(q_pos >= k_pos, s, NEG_INF)

  m_prev = jnp.max(m_ref[...], axis=-1, keepdims=True)   # [bq, 1]
  l_prev = jnp.max(l_ref[...], axis=-1, keepdims=True)
  m_block = jnp.max(s, axis=-1, keepdims=True)           # [bq, 1]
  m_new = jnp.maximum(m_prev, m_block)
  safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
  p = jnp.exp(s - safe_m)
  p = jnp.where(s <= NEG_INF / 2, 0.0, p)
  correction = jnp.exp(m_prev - safe_m)
  correction = jnp.where(m_prev <= NEG_INF / 2, 0.0, correction)
  l_new = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
  l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)
  m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
  acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
      p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                  l_ref, *, scale: float, causal: bool, block_q: int,
                  block_k: int):
  """One step of the k-outer / q-inner sweep within a q TILE.

  The grid is (bh, n_q_outer, n_k, n_q_inner): within one q tile
  (n_q_inner * block_q rows, accumulators resident in VMEM scratch),
  k is the outer loop — so Pallas fetches each k/v block ONCE per k
  step and the inner q sweep reuses it from VMEM. With q fully outer
  (the FlashAttention-1 order) every k/v block is re-fetched for every
  q block; at long L the kernel was bound by those copies, not the MXU
  (measured 12.7 ms at L=16k vs ~4 ms in this order). The q tile keeps
  scratch under the 16 MB scoped-VMEM limit; k/v blocks are re-fetched
  only once per TILE (L/tile times total).
  """
  i_qo = pl.program_id(1)
  i_k = pl.program_id(2)
  i_qi = pl.program_id(3)
  n_k = pl.num_programs(2)
  n_qi = pl.num_programs(3)
  i_q = i_qo * n_qi + i_qi            # global q-block index
  rows = pl.dslice(i_qi * block_q, block_q)

  @pl.when(i_k == 0)
  def _init():
    acc_ref[rows, :] = jnp.zeros((block_q, acc_ref.shape[-1]), jnp.float32)
    m_ref[rows, :] = jnp.full((block_q, 128), NEG_INF, jnp.float32)
    l_ref[rows, :] = jnp.zeros((block_q, 128), jnp.float32)

  def _do_update():
    # One shared numerics implementation (_block_update) for both this
    # kernel and the ring-carry kernel; the tile's accumulator rows are
    # exposed as sub-refs.
    _block_update(q_ref, k_ref, v_ref, acc_ref.at[rows, :],
                  m_ref.at[rows, :], l_ref.at[rows, :], scale=scale,
                  causal=causal, block_q=block_q, block_k=block_k,
                  q_offset=0, k_offset=0, i_q=i_q, i_k=i_k)

  if causal:
    # Skip blocks entirely above the causal diagonal (all scores -inf).
    @pl.when(i_q * block_q + block_q - 1 >= i_k * block_k)
    def _update():
      _do_update()
  else:
    _do_update()

  @pl.when(i_k == n_k - 1)
  def _finalize():
    l_col = jnp.max(l_ref[rows, :], axis=-1, keepdims=True)    # [bq, 1]
    m_col = jnp.max(m_ref[rows, :], axis=-1, keepdims=True)
    l_final = jnp.maximum(l_col, 1e-20)
    o_ref[0] = (acc_ref[rows, :] / l_final).astype(o_ref.dtype)
    # Log-sum-exp per row, saved for the backward pass (FlashAttention).
    # Broadcast over the 8 padding sublanes (see _flash_bhld's lse shape).
    row = (m_col + jnp.log(l_final))[:, 0]
    lse_ref[0] = jnp.broadcast_to(row[None, :], (8, block_q))


def _flash_bhld(q, k, v, *, scale: float, causal: bool, block_q: int,
                block_k: int, interpret: bool):
  """[BH, L, D] flash attention via pallas_call.

  The log-sum-exp output is materialized as [BH, 8, L] — Mosaic requires
  output blocks whose second-minor dim is divisible by 8 (or equals the
  array dim), so the per-row LSE is broadcast over 8 padding sublanes in
  the kernel and sliced back to [BH, L] here. The waste is 7 f32 rows per
  (bh, L): ~3.5 MB at bh=8, L=16k — noise next to the k/v tensors.
  """
  bh, l_q, d = q.shape
  l_k = k.shape[1]
  n_q = pl.cdiv(l_q, block_q)
  n_k = pl.cdiv(l_k, block_k)
  # q rows per tile: as many q blocks as fit a few MB of f32 accumulator
  # scratch AND divide n_q evenly (grid dims are rectangular).
  max_qi = max(1, (4096 // block_q))
  n_qi = max_qi
  while n_q % n_qi:
    n_qi -= 1
  n_qo = n_q // n_qi
  tile_rows = n_qi * block_q
  kernel = functools.partial(
      _flash_kernel, scale=scale, causal=causal, block_q=block_q,
      block_k=block_k)
  # Grid: per q TILE, k OUTER / q INNER (see _flash_kernel) — each k/v
  # block is fetched once per k step per tile; the tile's accumulators
  # live in VMEM scratch.
  out, lse8 = pl.pallas_call(
      kernel,
      grid=(bh, n_qo, n_k, n_qi),
      in_specs=[
          pl.BlockSpec((1, block_q, d),
                       lambda b, qo, j, qi, n=n_qi: (b, qo * n + qi, 0)),
          pl.BlockSpec((1, block_k, d), lambda b, qo, j, qi: (b, j, 0)),
          pl.BlockSpec((1, block_k, d), lambda b, qo, j, qi: (b, j, 0)),
      ],
      out_specs=[
          pl.BlockSpec((1, block_q, d),
                       lambda b, qo, j, qi, n=n_qi: (b, qo * n + qi, 0)),
          pl.BlockSpec((1, 8, block_q),
                       lambda b, qo, j, qi, n=n_qi: (b, 0, qo * n + qi)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct(q.shape, q.dtype),
          jax.ShapeDtypeStruct((bh, 8, l_q), jnp.float32),
      ],
      scratch_shapes=[
          pltpu.VMEM((tile_rows, d), jnp.float32),
          # 128 uniform lanes per scalar — see _block_update's m/l note.
          pltpu.VMEM((tile_rows, 128), jnp.float32),
          pltpu.VMEM((tile_rows, 128), jnp.float32),
      ],
      interpret=interpret,
  )(q, k, v)
  return out, lse8[:, 0, :]


def _flash_carry_kernel(offsets_ref, q_ref, k_ref, v_ref, o_in_ref,
                        m_in_ref, l_in_ref, o_out_ref, m_out_ref,
                        l_out_ref, acc_ref, m_ref, l_ref, *, scale: float,
                        causal: bool, block_q: int, block_k: int):
  """Flash block update with EXTERNAL accumulators (for ring attention).

  Like _flash_kernel but the online-softmax state (o, m, l) is carried in
  and out UNNORMALIZED — the ring loop feeds each hop's outputs into the
  next and normalizes once at the end. ``offsets_ref`` (scalar prefetch)
  holds the global (q_offset, k_offset) so causal masking sees global
  positions even though each device only holds its shard.
  """
  i_q = pl.program_id(1)
  i_k = pl.program_id(2)
  n_k = pl.num_programs(2)

  @pl.when(i_k == 0)
  def _init():
    acc_ref[:] = o_in_ref[0].astype(jnp.float32)
    # m/l ride in [1, 8, block_q] blocks (8 broadcast sublanes — Mosaic's
    # output-block divisibility rule; see _flash_bhld's lse note). Reduce
    # over the uniform sublanes rather than slicing one (width-1 memref
    # slices are rejected by jax 0.9 Mosaic), then broadcast across the
    # 128 scalar lanes of the scratch.
    m_col = jnp.max(m_in_ref[0].astype(jnp.float32), axis=0)[:, None]
    l_col = jnp.max(l_in_ref[0].astype(jnp.float32), axis=0)[:, None]
    m_ref[...] = jnp.broadcast_to(m_col, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_col, l_ref.shape)

  def _do_update():
    _block_update(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, scale=scale,
                  causal=causal, block_q=block_q, block_k=block_k,
                  q_offset=offsets_ref[0], k_offset=offsets_ref[1],
                  i_q=i_q, i_k=i_k)

  if causal:
    # Global-position block skip (offsets are traced scalars): the block
    # contributes nothing when its largest q position is left of its
    # smallest k position.
    @pl.when(offsets_ref[0] + i_q * block_q + block_q - 1
             >= offsets_ref[1] + i_k * block_k)
    def _update():
      _do_update()
  else:
    _do_update()

  @pl.when(i_k == n_k - 1)
  def _finalize():
    o_out_ref[0] = acc_ref[:]
    m_row = jnp.max(m_ref[...], axis=-1)                     # [bq]
    l_row = jnp.max(l_ref[...], axis=-1)
    m_out_ref[0] = jnp.broadcast_to(m_row[None, :], (8, block_q))
    l_out_ref[0] = jnp.broadcast_to(l_row[None, :], (8, block_q))


def flash_attention_carry(q, k, v, o, m, l, q_offset, k_offset,
                          causal: bool, scale: float,
                          block_q: int = 128, block_k: int = 128,
                          interpret: Optional[bool] = None):
  """One unnormalized flash update of (o, m, l) with a new k/v block.

  Shapes: q [BH, Lq, D]; k/v [BH, Lk, D]; o [BH, Lq, D] f32; m/l [BH, Lq]
  f32. ``q_offset``/``k_offset`` are traced global-position scalars.
  Returns updated (o, m, l). This is the ring-attention inner kernel;
  forward-only (no VJP) — the differentiable ring path is the jnp one.
  """
  if interpret is None:
    interpret = jax.default_backend() == 'cpu'
  bh, l_q, d = q.shape
  l_k = k.shape[1]
  block_q = min(block_q, l_q)
  block_k = min(block_k, l_k)
  if l_q % block_q or l_k % block_k:
    raise ValueError(
        'Shard lengths ({}, {}) must be multiples of the block sizes '
        '({}, {}).'.format(l_q, l_k, block_q, block_k))
  n_q = l_q // block_q
  n_k = l_k // block_k
  kernel = functools.partial(
      _flash_carry_kernel, scale=scale, causal=causal, block_q=block_q,
      block_k=block_k)
  offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                       jnp.asarray(k_offset, jnp.int32)])
  # m/l carries are padded to 8 broadcast sublanes for Mosaic's block
  # divisibility rule (same scheme as _flash_bhld's lse output).
  m8 = jnp.broadcast_to(m[:, None, :], (bh, 8, l_q))
  l8 = jnp.broadcast_to(l[:, None, :], (bh, 8, l_q))
  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=1,
      grid=(bh, n_q, n_k),
      # Index maps receive the scalar-prefetch ref as a trailing arg.
      in_specs=[
          pl.BlockSpec((1, block_q, d), lambda b, i, j, off: (b, i, 0)),
          pl.BlockSpec((1, block_k, d), lambda b, i, j, off: (b, j, 0)),
          pl.BlockSpec((1, block_k, d), lambda b, i, j, off: (b, j, 0)),
          pl.BlockSpec((1, block_q, d), lambda b, i, j, off: (b, i, 0)),
          pl.BlockSpec((1, 8, block_q), lambda b, i, j, off: (b, 0, i)),
          pl.BlockSpec((1, 8, block_q), lambda b, i, j, off: (b, 0, i)),
      ],
      out_specs=[
          pl.BlockSpec((1, block_q, d), lambda b, i, j, off: (b, i, 0)),
          pl.BlockSpec((1, 8, block_q), lambda b, i, j, off: (b, 0, i)),
          pl.BlockSpec((1, 8, block_q), lambda b, i, j, off: (b, 0, i)),
      ],
      scratch_shapes=[
          pltpu.VMEM((block_q, d), jnp.float32),
          pltpu.VMEM((block_q, 128), jnp.float32),
          pltpu.VMEM((block_q, 128), jnp.float32),
      ],
  )
  o_out, m_out8, l_out8 = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=[
          jax.ShapeDtypeStruct(o.shape, jnp.float32),
          jax.ShapeDtypeStruct((bh, 8, l_q), jnp.float32),
          jax.ShapeDtypeStruct((bh, 8, l_q), jnp.float32),
      ],
      interpret=interpret,
  )(offsets, q, k, v, o, m8, l8)
  return o_out, m_out8[:, 0, :], l_out8[:, 0, :]


# Backward block sizes are DECOUPLED from the forward defaults: the
# forward's (1024, 1024) tuning holds one [bq, bk] f32 score block; the
# backward holds four ([s, p, dp, ds]) plus two accumulator blocks, so the
# same sizes would 4x the peak VMEM and OOM at the L=32k headline case.
# Defaults are L-adaptive from a v5e sweep (B=1 H=8 D=128 causal, fwd+bwd
# chained): (256, 256) wins at L<=4k (7.1 vs 10.5 ms); (512, 1024) wins
# from 8k up (18.8/19.4/58.0 ms at 8k/16k/32k vs 20.4/20.4/60.8 for the
# flat 512s).


def _bwd_default_blocks(l_q: int, l_k: int):
  # Keyed on the LARGER side so cross-attention with mismatched lengths
  # lands in the regime its bigger grid actually runs in.
  return (256, 256) if max(l_q, l_k) <= 4096 else (512, 1024)


def _bwd_p_ds(q, k, v, do, lse, delta, *, scale, causal, q_base, k_base,
              block_q, block_k):
  """Shared recompute for both backward kernels: (p, ds) for one block
  pair, from the saved log-sum-exp. All operands f32 2D blocks."""
  s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32) * scale
  if causal:
    q_pos = q_base + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = k_base + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    s = jnp.where(q_pos >= k_pos, s, NEG_INF)
  p = jnp.exp(s - lse)
  if causal:
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
  dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                           preferred_element_type=jnp.float32)
  ds = p * (dp - delta) * scale
  return p, ds


def _flash_bwd_kv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                         causal: bool, block_q: int, block_k: int):
  """dk/dv: grid (bh, n_k, n_q) — k/v block resident (accumulators in
  scratch), q/do/lse/delta stream through."""
  i_k = pl.program_id(1)
  i_q = pl.program_id(2)
  n_q = pl.num_programs(2)

  @pl.when(i_q == 0)
  def _init():
    dk_acc[...] = jnp.zeros_like(dk_acc)
    dv_acc[...] = jnp.zeros_like(dv_acc)

  def _update():
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    # Reduce over the uniform broadcast sublanes instead of slicing one
    # (width-1 memref slices are rejected by jax 0.9 Mosaic).
    lse = jnp.max(lse_ref[0].astype(jnp.float32), axis=0)[:, None]
    delta = jnp.max(delta_ref[0].astype(jnp.float32), axis=0)[:, None]
    p, ds = _bwd_p_ds(q, k_ref[0].astype(jnp.float32),
                      v_ref[0].astype(jnp.float32), do, lse, delta,
                      scale=scale, causal=causal,
                      q_base=i_q * block_q, k_base=i_k * block_k,
                      block_q=block_q, block_k=block_k)
    dv_acc[...] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dk_acc[...] += jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

  if causal:
    # Blocks fully above the diagonal contribute nothing to dk/dv.
    @pl.when(i_q * block_q + block_q - 1 >= i_k * block_k)
    def _():
      _update()
  else:
    _update()

  @pl.when(i_q == n_q - 1)
  def _finalize():
    dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
    dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_q_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dq_ref, dq_acc, *, scale: float, causal: bool,
                        block_q: int, block_k: int):
  """dq: grid (bh, n_q, n_k) — q block resident, k/v stream through."""
  i_q = pl.program_id(1)
  i_k = pl.program_id(2)
  n_k = pl.num_programs(2)

  @pl.when(i_k == 0)
  def _init():
    dq_acc[...] = jnp.zeros_like(dq_acc)

  def _update():
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = jnp.max(lse_ref[0].astype(jnp.float32), axis=0)[:, None]
    delta = jnp.max(delta_ref[0].astype(jnp.float32), axis=0)[:, None]
    k = k_ref[0].astype(jnp.float32)
    _, ds = _bwd_p_ds(q, k, v_ref[0].astype(jnp.float32), do, lse, delta,
                      scale=scale, causal=causal,
                      q_base=i_q * block_q, k_base=i_k * block_k,
                      block_q=block_q, block_k=block_k)
    dq_acc[...] += jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

  if causal:
    @pl.when(i_q * block_q + block_q - 1 >= i_k * block_k)
    def _():
      _update()
  else:
    _update()

  @pl.when(i_k == n_k - 1)
  def _finalize():
    dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, d_out, *, scale, causal,
                      block_q, block_k, interpret):
  """Full Pallas backward: dq, dk, dv over [BH, L, D] operands.

  Two kernels (FlashAttention-2 structure): dk/dv with the k/v block
  resident and q streaming, dq with the q block resident and k/v
  streaming. P is recomputed from the forward's saved log-sum-exp; no
  [L, L] tensor exists in either pass. delta = rowsum(do * out) is one
  fused elementwise pass XLA handles before the kernels.
  """
  bh, l_q, d = q.shape
  l_k = k.shape[1]
  n_q = l_q // block_q
  n_k = l_k // block_k
  do = d_out.astype(jnp.float32)
  delta = jnp.sum(do * out.astype(jnp.float32), axis=-1)      # [BH, Lq]
  # lse/delta ride as [BH, 8, L] broadcast-sublane blocks (Mosaic's
  # second-minor divisibility rule — same scheme as the forward's lse).
  lse8 = jnp.broadcast_to(lse[:, None, :], (bh, 8, l_q))
  delta8 = jnp.broadcast_to(delta[:, None, :], (bh, 8, l_q))

  kv_kernel = functools.partial(
      _flash_bwd_kv_kernel, scale=scale, causal=causal, block_q=block_q,
      block_k=block_k)
  dk, dv = pl.pallas_call(
      kv_kernel,
      grid=(bh, n_k, n_q),
      in_specs=[
          pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
          pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
          pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
          pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
          pl.BlockSpec((1, 8, block_q), lambda b, j, i: (b, 0, i)),
          pl.BlockSpec((1, 8, block_q), lambda b, j, i: (b, 0, i)),
      ],
      out_specs=[
          pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
          pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct(k.shape, k.dtype),
          jax.ShapeDtypeStruct(v.shape, v.dtype),
      ],
      scratch_shapes=[
          pltpu.VMEM((block_k, d), jnp.float32),
          pltpu.VMEM((block_k, d), jnp.float32),
      ],
      interpret=interpret,
  )(q, k, v, d_out, lse8, delta8)

  q_kernel = functools.partial(
      _flash_bwd_q_kernel, scale=scale, causal=causal, block_q=block_q,
      block_k=block_k)
  dq = pl.pallas_call(
      q_kernel,
      grid=(bh, n_q, n_k),
      in_specs=[
          pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
          pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
          pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
          pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
          pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
          pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
      ],
      out_specs=[
          pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
      ],
      out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
      scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
      interpret=interpret,
  )(q, k, v, d_out, lse8, delta8)[0]
  return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_diff(q, k, v, causal, scale, block_q, block_k, interpret,
                block_q_bwd, block_k_bwd):
  """custom_vjp core over [BH, L, D] operands."""
  del block_q_bwd, block_k_bwd  # backward-only
  out, _ = _flash_bhld(q, k, v, scale=scale, causal=causal,
                       block_q=block_q, block_k=block_k,
                       interpret=interpret)
  return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               block_q_bwd, block_k_bwd):
  del block_q_bwd, block_k_bwd
  out, lse = _flash_bhld(q, k, v, scale=scale, causal=causal,
                         block_q=block_q, block_k=block_k,
                         interpret=interpret)
  return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, block_q_bwd,
               block_k_bwd, residuals, d_out):
  """Pallas FlashAttention-2 backward (see _flash_bwd_pallas).

  Until round 4 this was an XLA lax.scan recompute; it is now the same
  kernel family as the forward, with causal block skip and its own block
  sizes (_bwd_default_blocks — the forward's 1024 would 4x the
  backward's VMEM working set and OOM the L=32k case)."""
  q, k, v, out, lse = residuals
  l_q = q.shape[1]
  l_k = k.shape[1]
  default_bq, default_bk = _bwd_default_blocks(l_q, l_k)
  bq = _dividing_block_or_raise(min(block_q_bwd or default_bq, l_q), l_q)
  bk = _dividing_block_or_raise(min(block_k_bwd or default_bk, l_k), l_k)
  dq, dk, dv = _flash_bwd_pallas(
      q, k, v, out, lse, d_out, scale=scale, causal=causal,
      block_q=bq, block_k=bk, interpret=interpret)
  return dq, dk, dv


_flash_diff.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 1024,
                    block_k: int = 1024,
                    interpret: Optional[bool] = None,
                    block_q_bwd: Optional[int] = None,
                    block_k_bwd: Optional[int] = None):
  """Exact attention over [B, L, H, D] inputs, O(L) memory, differentiable.

  Forward runs the Pallas kernel (k-outer/q-inner tiled sweep, see
  _flash_kernel); the backward is the blockwise FlashAttention
  recomputation (custom VJP) so training never sees an [L, L] tensor
  either. Blocks step down automatically to sizes dividing L.
  ``interpret=None`` auto-selects the Pallas interpreter off-TPU so
  tests run on CPU.

  Default block sizes come from v5e sweeps (B=1, H=8, D=128, causal,
  chained on-device timing): (1024, 1024) — grid-step count (fixed
  per-step overhead) and k/v re-fetch traffic are the levers, so bigger
  blocks win until the f32 score matrix presses the 16 MB scoped-VMEM
  limit. Measured ms in docs/performance.md.

  Head dims below 128 are zero-padded up to 128 for the kernels: jax
  0.9's Mosaic rejects memref slices whose lane extent is not 128-aligned,
  which the accumulator sub-refs need. Exact — zero k/v columns change
  neither scores nor outputs; padding/slicing happens outside the
  custom_vjp so the backward sees the padded problem and autodiff of the
  pad/slice restores [.., d] gradients.
  """
  if scale is None:
    scale = 1.0 / float(np.sqrt(q.shape[-1]))
  if interpret is None:
    interpret = jax.default_backend() == 'cpu'
  b, l_q, h, d = q.shape
  l_k = k.shape[1]
  if jnp.dtype(q.dtype).itemsize >= 4:
    # f32 operands double the VMEM block footprint; the bf16-tuned
    # (1024, 1024) defaults press past the 16 MB scoped-VMEM limit at
    # L>=4096 (measured: 'Scoped allocation ... exceeded scoped vmem
    # limit'). Conservative caps keep the f32 working set a few MB.
    block_q = min(block_q, 256)
    block_k = min(block_k, 512)

  block_q = _dividing_block_or_raise(min(block_q, l_q), l_q)
  block_k = _dividing_block_or_raise(min(block_k, l_k), l_k)

  dp = -(-d // 128) * 128 if not interpret else d

  def _to_bhld(x):
    x = x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)
    if dp != d:
      x = jnp.pad(x, ((0, 0), (0, 0), (0, dp - d)))
    return x

  out = _flash_diff(_to_bhld(q), _to_bhld(k), _to_bhld(v), causal, scale,
                    block_q, block_k, interpret, block_q_bwd, block_k_bwd)
  out = out[:, :, :d] if dp != d else out
  return out.reshape(b, h, l_q, d).transpose(0, 2, 1, 3)
