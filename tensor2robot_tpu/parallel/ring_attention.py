"""Ring attention: exact attention over sequences sharded across devices.

Long-context support absent from the reference (SURVEY.md §5: episodes were
<=40 steps, LSTM/TCN-based) but first-class here: sequences shard over a mesh
axis, each device holds a [B, L/N, H, D] block of q/k/v, and key/value blocks
rotate around the ring via ``ppermute`` (ICI neighbor hops) while a
flash-style online softmax accumulates exact results — O(L/N) memory per
device, N overlappable ICI hops, no approximation.

Reference technique: Ring Attention with Blockwise Transformers (Liu et al.,
arXiv:2310.01889); implementation here is shard_map + lax.fori_loop with
log-sum-exp accumulation in float32.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tensor2robot_tpu.parallel.collectives import shard_map_compat

NEG_INF = -1e30


def _block_attention(q, k, v, o, m, l, q_offset, k_offset, causal,
                     scale):
  """One q-block x k-block update of the online-softmax accumulators.

  Shapes: q [B,Lq,H,D], k/v [B,Lk,H,D]; accumulators o [B,Lq,H,D] (f32),
  m/l [B,Lq,H] (f32). Returns updated (o, m, l).
  """
  qf = q.astype(jnp.float32)
  kf = k.astype(jnp.float32)
  vf = v.astype(jnp.float32)
  # scores: [B, H, Lq, Lk]
  scores = jnp.einsum('bqhd,bkhd->bhqk', qf, kf) * scale
  if causal:
    q_pos = q_offset + jnp.arange(q.shape[1])
    k_pos = k_offset + jnp.arange(k.shape[1])
    mask = q_pos[:, None] >= k_pos[None, :]
    scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
  m_block = jnp.max(scores, axis=-1)                      # [B,H,Lq]
  m_block = jnp.transpose(m_block, (0, 2, 1))             # [B,Lq,H]
  m_new = jnp.maximum(m, m_block)
  # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1.
  safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
  p = jnp.exp(scores - jnp.transpose(safe_m, (0, 2, 1))[:, :, :, None])
  p = jnp.where(scores <= NEG_INF / 2, 0.0, p)            # masked entries
  correction = jnp.exp(m - safe_m)
  correction = jnp.where(m <= NEG_INF / 2, 0.0, correction)
  l_new = l * correction + jnp.transpose(jnp.sum(p, axis=-1), (0, 2, 1))
  pv = jnp.einsum('bhqk,bkhd->bqhd', p, vf)               # [B,Lq,H,D]
  o_new = o * correction[:, :, :, None] + pv
  return o_new, m_new, l_new


def _ring_forward(q, k, v, axis_name: str, causal: bool,
                  scale: float, use_pallas: bool):
  """Per-shard forward: local q attends to every k/v block as it rings
  past. Returns (out [B,Lq,H,D], lse [B,Lq,H] f32) — the log-sum-exp is
  the residual the memory-efficient backward recomputes p from."""
  axis_size = lax.psum(1, axis_name)
  my_index = lax.axis_index(axis_name)
  block_q = q.shape[1]
  block_k = k.shape[1]

  if use_pallas:
    return _ring_shard_pallas(q, k, v, axis_name, causal, scale,
                              axis_size, my_index)

  o = jnp.zeros(q.shape, jnp.float32)
  m = jnp.full(q.shape[:2] + (q.shape[2],), NEG_INF, jnp.float32)
  l = jnp.zeros(q.shape[:2] + (q.shape[2],), jnp.float32)

  def body(i, carry):
    o, m, l, k_cur, v_cur = carry
    src = (my_index - i) % axis_size  # whose block we currently hold
    o, m, l = _block_attention(
        q, k_cur, v_cur, o, m, l,
        q_offset=my_index * block_q, k_offset=src * block_k,
        causal=causal, scale=scale)
    # Rotate k/v to the next device; last iteration's rotate restores the
    # originals (harmless, lets XLA overlap the hop with block compute).
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    k_next = lax.ppermute(k_cur, axis_name, perm)
    v_next = lax.ppermute(v_cur, axis_name, perm)
    return o, m, l, k_next, v_next

  o, m, l, _, _ = lax.fori_loop(0, axis_size, body, (o, m, l, k, v))
  lse = m + jnp.log(jnp.maximum(l, 1e-30))
  l = jnp.maximum(l, 1e-20)
  return (o / l[:, :, :, None]).astype(q.dtype), lse


def _ring_backward(axis_name, causal, scale, res, dout):
  """Memory-efficient ring backward: recompute p blockwise per hop from
  the saved log-sum-exp (never materializing more than one [Lq, Lk]
  score block), and let the dk/dv accumulators RIDE THE RING with their
  k/v blocks — after axis_size hops each accumulator is back on its home
  device having collected contributions from every q shard. Per-device
  persistent memory stays O(L/N * D), like the forward.
  """
  q, k, v, out, lse = res
  axis_size = lax.psum(1, axis_name)
  my_index = lax.axis_index(axis_name)
  block_q, block_k = q.shape[1], k.shape[1]
  qf = q.astype(jnp.float32)
  do = dout.astype(jnp.float32)
  # delta[b,q,h] = sum_d do*out — the softmax-jacobian diagonal term.
  delta = jnp.sum(do * out.astype(jnp.float32), axis=-1)
  delta_bhq = jnp.transpose(delta, (0, 2, 1))[:, :, :, None]
  safe_lse = jnp.where(lse <= NEG_INF / 2, 0.0, lse)
  lse_bhq = jnp.transpose(safe_lse, (0, 2, 1))[:, :, :, None]

  def body(i, carry):
    dq, dk_cur, dv_cur, k_cur, v_cur = carry
    src = (my_index - i) % axis_size
    kf = k_cur.astype(jnp.float32)
    vf = v_cur.astype(jnp.float32)
    scores = jnp.einsum('bqhd,bkhd->bhqk', qf, kf) * scale
    if causal:
      q_pos = my_index * block_q + jnp.arange(block_q)
      k_pos = src * block_k + jnp.arange(block_k)
      mask = q_pos[:, None] >= k_pos[None, :]
      scores = jnp.where(mask[None, None], scores, NEG_INF)
    p = jnp.exp(scores - lse_bhq)
    p = jnp.where(scores <= NEG_INF / 2, 0.0, p)
    dv_cur = dv_cur + jnp.einsum('bhqk,bqhd->bkhd', p, do)
    dp = jnp.einsum('bqhd,bkhd->bhqk', do, vf)
    ds = p * (dp - delta_bhq)
    dq = dq + jnp.einsum('bhqk,bkhd->bqhd', ds, kf) * scale
    dk_cur = dk_cur + jnp.einsum('bhqk,bqhd->bkhd', ds, qf) * scale
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    return (dq,
            lax.ppermute(dk_cur, axis_name, perm),
            lax.ppermute(dv_cur, axis_name, perm),
            lax.ppermute(k_cur, axis_name, perm),
            lax.ppermute(v_cur, axis_name, perm))

  dq = jnp.zeros(q.shape, jnp.float32)
  dkv = jnp.zeros(k.shape, jnp.float32)
  dq, dk, dv, _, _ = lax.fori_loop(
      0, axis_size, body, (dq, dkv, dkv, k, v))
  return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_attention_shard(q, k, v, axis_name: str, causal: bool,
                          scale: float, use_pallas: bool):
  """Differentiable per-shard ring attention (see _ring_forward)."""
  out, _ = _ring_forward(q, k, v, axis_name, causal, scale, use_pallas)
  return out


def _ring_shard_fwd(q, k, v, axis_name, causal, scale, use_pallas):
  out, lse = _ring_forward(q, k, v, axis_name, causal, scale, use_pallas)
  return out, (q, k, v, out, lse)


def _ring_shard_bwd(axis_name, causal, scale, use_pallas, res, dout):
  del use_pallas  # backward is the blockwise jnp path either way
  return _ring_backward(axis_name, causal, scale, res, dout)


_ring_attention_shard.defvjp(_ring_shard_fwd, _ring_shard_bwd)


def _ring_shard_pallas(q, k, v, axis_name: str, causal: bool, scale: float,
                       axis_size, my_index):
  """Pallas-kernel ring body, entirely in the kernel's [B*H, L, D] layout.

  q and the accumulators are converted ONCE before the loop and back once
  after; only k/v (which must rotate anyway) ride the ring. Forward-only —
  ring_self_attention keeps the jnp path for differentiation.
  """
  from tensor2robot_tpu.parallel.flash_attention import (
      flash_attention_carry,
  )
  b, block_q, h, d = q.shape
  block_k = k.shape[1]

  def _to_bhld(x):
    return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

  q_bhld = _to_bhld(q)
  o = jnp.zeros(q_bhld.shape, jnp.float32)
  m = jnp.full(q_bhld.shape[:2], NEG_INF, jnp.float32)
  l = jnp.zeros(q_bhld.shape[:2], jnp.float32)

  def body(i, carry):
    o, m, l, k_cur, v_cur = carry
    src = (my_index - i) % axis_size
    o, m, l = flash_attention_carry(
        q_bhld, k_cur, v_cur, o, m, l,
        q_offset=my_index * block_q, k_offset=src * block_k,
        causal=causal, scale=scale)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    k_next = lax.ppermute(k_cur, axis_name, perm)
    v_next = lax.ppermute(v_cur, axis_name, perm)
    return o, m, l, k_next, v_next

  o, m, l, _, _ = lax.fori_loop(
      0, axis_size, body, (o, m, l, _to_bhld(k), _to_bhld(v)))
  lse = m + jnp.log(jnp.maximum(l, 1e-30))           # [B*H, Lq]
  l = jnp.maximum(l, 1e-20)
  out = o / l[:, :, None]
  out = out.reshape(b, h, block_q, d).transpose(0, 2, 1, 3).astype(q.dtype)
  lse = lse.reshape(b, h, block_q).transpose(0, 2, 1)  # [B, Lq, H]
  return out, lse


def ring_self_attention(q, k, v, mesh: Mesh, seq_axis: str = 'data',
                        causal: bool = False,
                        scale: Optional[float] = None,
                        use_pallas: Optional[bool] = None):
  """Exact attention with q/k/v sequence-sharded over ``seq_axis``.

  Args:
    q, k, v: [B, L, H, D] arrays (globally); L shards over ``seq_axis``.
    mesh: the device mesh.
    seq_axis: mesh axis carrying sequence blocks.
    causal: apply a causal mask over *global* positions.
    scale: score scale; default 1/sqrt(D).
    use_pallas: run each intra-shard FORWARD block update through the
      Pallas flash kernel (parallel/flash_attention.py) — no per-hop
      [Lq, Lk] score tensor in HBM. Requires per-device shard lengths
      divisible by the kernel block sizes (<=128). Fully trainable
      either way: the custom VJP recomputes p blockwise per hop from
      the saved log-sum-exp and rotates the dk/dv accumulators around
      the ring with their blocks, so TRAINING memory is O(L/N) per
      device too (plain autodiff through the hop loop would have saved
      every per-hop score tensor).

  Returns [B, L, H, D], sharded like q.
  """
  if scale is None:
    scale = 1.0 / (q.shape[-1] ** 0.5)
  if use_pallas is None:
    use_pallas = False
  if use_pallas:
    axis_size = mesh.shape[seq_axis]
    shard_len = q.shape[1] // axis_size
    if shard_len % min(128, shard_len) != 0:
      raise ValueError(
          'use_pallas requires per-device shard length ({}) divisible by '
          'the kernel block size.'.format(shard_len))
  spec = P(None, seq_axis, None, None)
  fn = shard_map_compat(
      functools.partial(_ring_attention_shard, axis_name=seq_axis,
                        causal=causal, scale=scale, use_pallas=use_pallas),
      mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
      check_vma=False)
  return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = False,
                        scale: Optional[float] = None):
  """Single-device exact attention — the numerics oracle for tests."""
  if scale is None:
    scale = 1.0 / (q.shape[-1] ** 0.5)
  scores = jnp.einsum('bqhd,bkhd->bhqk', q.astype(jnp.float32),
                      k.astype(jnp.float32)) * scale
  if causal:
    mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
    scores = jnp.where(mask[None, None], scores, NEG_INF)
  weights = jax.nn.softmax(scores, axis=-1)
  out = jnp.einsum('bhqk,bkhd->bqhd', weights, v.astype(jnp.float32))
  return out.astype(q.dtype)
