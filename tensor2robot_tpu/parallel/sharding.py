"""Sharding rules and host↔device placement helpers.

The JAX analog of the reference's TPU input plumbing (per-host input_fn +
infeed, utils/tfdata.py:43-66) and of CrossShardOptimizer's implicit
replication contract: batches are sharded over 'data', parameters are
replicated (or FSDP-sharded over 'fsdp'), and every jitted step's gradient
psum is derived by XLA from these placements.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensor2robot_tpu.parallel.mesh import DATA_AXIS, FSDP_AXIS


def batch_sharding(mesh: Mesh) -> NamedSharding:
  """Leading dim sharded over the data axis."""
  return NamedSharding(mesh, P(DATA_AXIS))

def replicated(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, P())


def fsdp_param_spec(param, mesh: Mesh,
                    min_size_to_shard: int = 2 ** 14) -> P:
  """Zero-style param sharding: shard the largest dim divisible by |fsdp|.

  Small params stay replicated — sharding them would cost more in
  all-gather latency than the memory saved.
  """
  size = int(mesh.shape.get(FSDP_AXIS, 1))
  if size <= 1 or param.size < min_size_to_shard:
    return P()
  shape = param.shape
  candidates = sorted(range(len(shape)), key=lambda i: -shape[i])
  for dim in candidates:
    if shape[dim] % size == 0:
      spec = [None] * len(shape)
      spec[dim] = FSDP_AXIS
      return P(*spec)
  return P()


def train_state_sharding(state, mesh: Mesh,
                         use_fsdp: bool = False):
  """Sharding pytree for a TrainState: replicated, or FSDP for params/opt."""
  def _spec(leaf):
    if use_fsdp and hasattr(leaf, 'shape') and hasattr(leaf, 'size'):
      return NamedSharding(mesh, fsdp_param_spec(leaf, mesh))
    return NamedSharding(mesh, P())
  return jax.tree.map(_spec, state)


def shard_batch(batch, mesh: Mesh):
  """Places a host-global numpy batch onto the mesh, sharded over 'data'.

  Single-process path: device_put with a data sharding. Multi-process path:
  each host holds its slice of the global batch and
  ``make_array_from_process_local_data`` assembles the global array (the
  JAX analog of per-host infeed, PER_HOST_V2).
  """
  sharding = batch_sharding(mesh)
  if jax.process_count() == 1:
    return jax.device_put(batch, sharding)

  def _make(x):
    x = np.asarray(x)
    return jax.make_array_from_process_local_data(sharding, x)
  return jax.tree.map(_make, batch)


def global_batch_size_per_host(global_batch_size: int) -> int:
  """Per-host slice of the global batch (ref get_batch_size, tfdata.py:43)."""
  n = jax.process_count()
  if global_batch_size % n:
    raise ValueError(
        'Global batch size {} not divisible by host count {}.'.format(
            global_batch_size, n))
  return global_batch_size // n
