"""Sharding rules and host↔device placement helpers.

The JAX analog of the reference's TPU input plumbing (per-host input_fn +
infeed, utils/tfdata.py:43-66) and of CrossShardOptimizer's implicit
replication contract: batches are sharded over 'data', parameters are
replicated (or FSDP-sharded over 'fsdp'), and every jitted step's gradient
psum is derived by XLA from these placements.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensor2robot_tpu.parallel.mesh import (DATA_AXIS, EXPERT_AXIS,
                                             FSDP_AXIS, MODEL_AXIS,
                                             PIPE_AXIS)


def constrain(x, mesh: Optional[Mesh], spec: P):
  """with_sharding_constraint when a mesh is live; identity otherwise.

  Shared by the TP/EP layer paths (layers/transformer.py, layers/moe.py)
  so activation-placement handling stays in one place.
  """
  if mesh is None:
    return x
  return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def batch_sharding(mesh: Mesh) -> NamedSharding:
  """Leading dim sharded over the data axis."""
  return NamedSharding(mesh, P(DATA_AXIS))

def replicated(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, P())


def fsdp_param_spec(param, mesh: Mesh,
                    min_size_to_shard: int = 2 ** 14) -> P:
  """Zero-style param sharding: shard the largest dim divisible by |fsdp|.

  Small params stay replicated — sharding them would cost more in
  all-gather latency than the memory saved.
  """
  size = int(mesh.shape.get(FSDP_AXIS, 1))
  if size <= 1 or param.size < min_size_to_shard:
    return P()
  shape = param.shape
  candidates = sorted(range(len(shape)), key=lambda i: -shape[i])
  for dim in candidates:
    if shape[dim] % size == 0:
      spec = [None] * len(shape)
      spec[dim] = FSDP_AXIS
      return P(*spec)
  return P()


# Megatron-style tensor-parallel rules for layers/transformer.py modules:
# qkv columns are head-major (kernel [d, H*3*Dh]) so sharding the output
# dim over 'model' splits whole heads; the out/mlp_out kernels shard their
# INPUT dim, making each device's contribution a partial sum that XLA
# closes with a psum over 'model' (the Megatron f/g collectives, derived
# by GSPMD from these placements instead of hand-written all-reduces).
TP_RULES_TRANSFORMER: Tuple[Tuple[str, P], ...] = (
    (r'(?!.*pipe_blocks).*/attn/qkv/kernel$', P(None, MODEL_AXIS)),
    (r'(?!.*pipe_blocks).*/attn/qkv/bias$', P(MODEL_AXIS)),
    (r'(?!.*pipe_blocks).*/attn/out/kernel$', P(MODEL_AXIS, None)),
    (r'(?!.*pipe_blocks).*/mlp_in/kernel$', P(None, MODEL_AXIS)),
    (r'(?!.*pipe_blocks).*/mlp_in/bias$', P(MODEL_AXIS)),
    (r'(?!.*pipe_blocks).*/mlp_out/kernel$', P(MODEL_AXIS, None)),
)


# Expert-parallel rules for layers/moe.py: the stacked per-expert MLP
# kernels shard their leading expert dim; the router stays replicated.
EP_RULES_MOE: Tuple[Tuple[str, P], ...] = (
    (r'.*/moe/w_in$', P(EXPERT_AXIS, None, None)),
    (r'.*/moe/w_out$', P(EXPERT_AXIS, None, None)),
)


# Pipeline-parallel rules for CausalTransformer(pipe_axis=...): every leaf
# under the stacked 'pipe_blocks' param leads with the stage dim, sharded
# over 'pipe' (parallel/pipeline.py). Order-independent when combined with
# the TP rules: those exclude pipe_blocks paths outright (negative
# lookahead), and a declining rule falls through to later rules anyway.
PP_RULES_TRANSFORMER: Tuple[Tuple[str, P], ...] = (
    (r'.*/pipe_blocks/.*', P(PIPE_AXIS)),
)


def _path_str(path) -> str:
  parts = []
  for entry in path:
    if hasattr(entry, 'key'):
      parts.append(str(entry.key))
    elif hasattr(entry, 'idx'):
      parts.append(str(entry.idx))
    elif hasattr(entry, 'name'):
      parts.append(str(entry.name))
    else:
      parts.append(str(entry))
  return '/'.join(parts)


def tp_param_spec(path_str: str, param, mesh: Mesh,
                  rules: Sequence[Tuple[str, P]]) -> Optional[P]:
  """First matching model-parallel rule whose axes divide the param.

  Works for any rule set naming mesh axes (TP_RULES_TRANSFORMER over
  'model', EP_RULES_MOE over 'expert', or user rules); a rule declines
  (param stays on the fallback path) when its axes are absent/size-1 in
  the mesh or don't divide the param's dims.
  """
  shape = getattr(param, 'shape', ())
  for pattern, spec in rules:
    if not re.match(pattern, path_str):
      continue
    if len(spec) > len(shape):
      continue  # rule shaped for a different rank: try later rules
    sharded_any = False
    ok = True
    for dim, axis in enumerate(spec):
      if axis is None:
        continue
      size = int(mesh.shape.get(axis, 1))
      if size <= 1 or shape[dim] % size:
        ok = False  # indivisible: replicate rather than mis-shard
        break
      sharded_any = True
    if ok and sharded_any:
      return spec
  return None


def train_state_sharding(state, mesh: Mesh,
                         use_fsdp: bool = False,
                         tp_rules: Optional[Sequence[Tuple[str, P]]] = None):
  """Sharding pytree for a TrainState: replicated, FSDP, and/or TP.

  ``tp_rules``: (path regex, PartitionSpec) pairs (e.g.
  TP_RULES_TRANSFORMER) matched against '/'-joined tree paths; matching
  params take the TP spec, everything else falls back to FSDP (if
  enabled) then replication. A param is never sharded on both — TP params
  are already split |model|-ways, and stacking 'fsdp' on their other dim
  would fragment the matmul tiles XLA feeds the MXU.
  """
  leaves, treedef = jax.tree_util.tree_flatten_with_path(state)

  def _spec(path, leaf):
    if hasattr(leaf, 'shape') and hasattr(leaf, 'size'):
      if tp_rules:
        tp = tp_param_spec(_path_str(path), leaf, mesh, tp_rules)
        if tp is not None:
          return NamedSharding(mesh, tp)
      if use_fsdp:
        return NamedSharding(mesh, fsdp_param_spec(leaf, mesh))
    return NamedSharding(mesh, P())

  return jax.tree_util.tree_unflatten(
      treedef, [_spec(path, leaf) for path, leaf in leaves])


def shard_batch(batch, mesh: Mesh):
  """Places a host-global numpy batch onto the mesh, sharded over 'data'.

  Single-process path: device_put with a data sharding. Multi-process path:
  each host holds its slice of the global batch and
  ``make_array_from_process_local_data`` assembles the global array (the
  JAX analog of per-host infeed, PER_HOST_V2).
  """
  sharding = batch_sharding(mesh)
  if jax.process_count() == 1:
    return jax.device_put(batch, sharding)

  def _make(x):
    x = np.asarray(x)
    return jax.make_array_from_process_local_data(sharding, x)
  return jax.tree.map(_make, batch)


def global_batch_size_per_host(global_batch_size: int) -> int:
  """Per-host slice of the global batch (ref get_batch_size, tfdata.py:43)."""
  n = jax.process_count()
  if global_batch_size % n:
    raise ValueError(
        'Global batch size {} not divisible by host count {}.'.format(
            global_batch_size, n))
  return global_batch_size // n
