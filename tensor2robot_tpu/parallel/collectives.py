"""Collective helpers over the mesh (the framework's communication backend).

The reference has no in-repo communication layer — TF1 gRPC/TPU all-reduce
did it invisibly (SURVEY.md §2.9/§5). Here the backend is explicit and tiny:
XLA collectives over mesh axes, riding ICI within a slice and DCN across
slices. These wrappers exist so higher layers (trainer, meta-learning, ring
attention) never hand-roll shard_map plumbing.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AxisName = Union[str, Sequence[str]]


def shard_map_compat(fn, mesh: Mesh, in_specs, out_specs,
                     check_vma: bool = False):
  """``shard_map`` across jax versions.

  jax >= 0.7 exposes ``jax.shard_map(..., check_vma=...)``; on 0.4.x the
  same transform lives in ``jax.experimental.shard_map`` and the kwarg is
  named ``check_rep``. Plain ``jax.shard_map`` attribute access on 0.4.x
  raises (deprecation-gated), so probe with getattr.
  """
  top_level = getattr(jax, 'shard_map', None)
  if top_level is not None:
    return top_level(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=check_vma)
  from jax.experimental.shard_map import shard_map

  return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma)


def pmean(value, axis_name: AxisName):
  return lax.pmean(value, axis_name)


def psum(value, axis_name: AxisName):
  return lax.psum(value, axis_name)


def all_gather(value, axis_name: AxisName, axis: int = 0,
               tiled: bool = True):
  return lax.all_gather(value, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(value, axis_name: AxisName, axis: int = 0):
  return lax.psum_scatter(value, axis_name, scatter_dimension=axis,
                          tiled=True)


def ring_permute(value, axis_name: str, shift: int = 1):
  """Sends ``value`` to the next device along a ring (ppermute over ICI)."""
  n = lax.psum(1, axis_name)
  perm = [(i, (i + shift) % n) for i in range(n)]
  return lax.ppermute(value, axis_name, perm)


def cross_replica_mean(tree, axis_name: AxisName = 'data'):
  """Mean of every leaf across the axis — e.g. batch-stat sync.

  The explicit form of what pjit inserts for gradients automatically.
  """
  return jax.tree.map(lambda x: lax.pmean(x, axis_name), tree)


def sharded_fn(mesh: Mesh, in_specs, out_specs,
               check_vma: bool = False) -> Callable:
  """Decorator: run a function per-shard with explicit collectives.

  Thin veneer over ``jax.shard_map`` so call sites read declaratively.
  """
  def decorator(fn):
    return shard_map_compat(fn, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, check_vma=check_vma)
  return decorator
