"""Device-mesh construction: ICI-major layouts, DCN-aware multi-slice meshes.

The reference delegates all distribution to TF1 (SURVEY.md §2.9): TPUEstimator
replication + CrossShardOptimizer all-reduce. Here the mesh IS the
communication backend: axes declared once, shardings annotated on arrays, and
XLA inserts psum/all-gather/reduce-scatter collectives over ICI (intra-slice)
or DCN (inter-slice) based on the mesh layout.

Axis convention (used across the framework):
  * 'data'  — batch (data parallel); gradients psum here.
  * 'fsdp'  — optional parameter sharding axis (zero-style), ICI-local.
  * 'model' — tensor parallelism for layers that opt in.
  * 'expert' — expert parallelism for MoE layers (layers/moe.py): the
    stacked expert params and the [E, ...] dispatch activations shard
    here; GSPMD lowers the dispatch/combine einsums to all-to-alls.
Sequence parallelism ('sp') reuses the 'data' axis via
parallel.ring_attention — sequence blocks ride the same ring.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

DATA_AXIS = 'data'
FSDP_AXIS = 'fsdp'
MODEL_AXIS = 'model'
EXPERT_AXIS = 'expert'
PIPE_AXIS = 'pipe'
DEFAULT_AXES = (DATA_AXIS, FSDP_AXIS, MODEL_AXIS, EXPERT_AXIS, PIPE_AXIS)


def create_mesh(axis_sizes: Optional[Dict[str, int]] = None,
                devices: Optional[Sequence] = None,
                allow_split_physical_axes: bool = False) -> Mesh:
  """Builds a Mesh with the framework's axis names.

  ``axis_sizes`` maps axis name -> size; one axis may be -1 (filled with the
  remaining devices). Default: all devices on 'data'. Device order comes from
  ``mesh_utils.create_device_mesh`` so that the innermost axes land on
  physically adjacent chips (ICI neighbors) — keeping model/fsdp collectives
  on the fastest links.
  """
  devices = list(devices if devices is not None else jax.devices())
  n = len(devices)
  axis_sizes = dict(axis_sizes or {DATA_AXIS: -1})
  for name in DEFAULT_AXES:
    axis_sizes.setdefault(name, 1)
  unknown = [k for k, v in axis_sizes.items() if v == -1]
  if len(unknown) > 1:
    raise ValueError('At most one axis may be -1; got {}.'.format(unknown))
  known = int(np.prod([v for v in axis_sizes.values() if v != -1]))
  if unknown:
    if n % known:
      raise ValueError(
          'Cannot infer {}: {} devices not divisible by {}.'.format(
              unknown[0], n, known))
    axis_sizes[unknown[0]] = n // known
  total = int(np.prod(list(axis_sizes.values())))
  if total != n:
    raise ValueError(
        'Mesh axes {} require {} devices but {} are available.'.format(
            axis_sizes, total, n))
  # Order axes: data outermost, model innermost (fastest links).
  names = [a for a in DEFAULT_AXES if a in axis_sizes]
  names += [a for a in axis_sizes if a not in names]
  shape = [axis_sizes[a] for a in names]
  try:
    device_array = mesh_utils.create_device_mesh(
        shape, devices=devices,
        allow_split_physical_axes=allow_split_physical_axes)
  except (ValueError, AssertionError):
    device_array = np.asarray(devices).reshape(shape)
  return Mesh(device_array, tuple(names))


def create_hybrid_mesh(ici_axis_sizes: Dict[str, int],
                       dcn_axis_sizes: Dict[str, int]) -> Mesh:
  """Multi-slice mesh: DCN axes outermost, ICI axes innermost.

  E.g. 4 v5e slices of 64 chips, data-parallel across slices, fsdp inside:
  ``create_hybrid_mesh({'fsdp': 64}, {'data': 4})`` — gradient psums then
  decompose into an ICI reduce-scatter + small DCN all-reduce, which is the
  layout that keeps the slow DCN hops to O(params/slice) bytes.
  """
  names = list(dcn_axis_sizes) + [a for a in ici_axis_sizes
                                  if a not in dcn_axis_sizes]
  ici_shape = [ici_axis_sizes.get(a, 1) for a in names]
  dcn_shape = [dcn_axis_sizes.get(a, 1) for a in names]
  device_array = mesh_utils.create_hybrid_device_mesh(
      ici_shape, dcn_shape)
  return Mesh(device_array, tuple(names))
