"""Parallelism: mesh construction, sharding rules, collectives, ring attention."""

from tensor2robot_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    create_hybrid_mesh,
    create_mesh,
)
from tensor2robot_tpu.parallel.sharding import (
    EP_RULES_MOE,
    PP_RULES_TRANSFORMER,
    TP_RULES_TRANSFORMER,
    batch_sharding,
    fsdp_param_spec,
    global_batch_size_per_host,
    replicated,
    shard_batch,
    train_state_sharding,
)
from tensor2robot_tpu.parallel import collectives
from tensor2robot_tpu.parallel import pipeline
from tensor2robot_tpu.parallel.flash_attention import flash_attention
from tensor2robot_tpu.parallel.ring_attention import (
    reference_attention,
    ring_self_attention,
)
