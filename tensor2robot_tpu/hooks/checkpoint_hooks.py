"""Export hooks reproducing the export-dir + lagged-dir filesystem contracts.

Parity target: /root/reference/hooks/checkpoint_hooks.py:36-206.
  * CheckpointExportListener (:56-93): after every checkpoint save, write a
    serving artifact so robot-side predictors can poll fresh weights during
    training.
  * LaggedCheckpointListener (:96-206): additionally maintain a
    one-version-LAGGED export dir — TD3 target networks implemented through
    the filesystem: actors read the lagged dir for the target Q.
  * _DirectoryVersionGC (:36): bounded version retention in both dirs.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

import jax

from tensor2robot_tpu.export import export_generators
from tensor2robot_tpu.hooks.hook_builder import TrainHook

# ref _DirectoryVersionGC (:36): bounded retention, shared with exporters.
_gc_versions = export_generators.garbage_collect_versions


class CheckpointExportHook(TrainHook):
  """Exports a serving artifact every ``export_every_steps`` (ref :56-93)."""

  def __init__(self,
               export_dir: str,
               export_every_steps: int = 500,
               exports_to_keep: int = 5,
               export_generator=None,
               batch_size: int = 1):
    self._export_dir = export_dir
    self._export_every_steps = export_every_steps
    self._exports_to_keep = exports_to_keep
    self._export_generator = (export_generator or
                              export_generators.DefaultExportGenerator())
    self._batch_size = batch_size
    self._last_exported_step: Optional[int] = None

  @property
  def export_dir(self) -> str:
    return self._export_dir

  def _export(self, trainer, state) -> Optional[str]:
    step = int(jax.device_get(state.step))
    if step == self._last_exported_step:
      return None
    self._export_generator.set_specification_from_model(trainer.model)
    variables = jax.device_get(
        state.variables(use_avg_params=trainer.model.use_avg_model_params))
    path = self._export_generator.export(
        self._export_dir, variables, step, batch_size=self._batch_size)
    self._last_exported_step = step
    self._after_export(path)
    _gc_versions(self._export_dir, self._exports_to_keep)
    return path

  def _after_export(self, path: str) -> None:
    pass

  def after_step(self, trainer, state, step: int, metrics) -> None:
    if step % self._export_every_steps == 0:
      self._export(trainer, state)

  def end(self, trainer, state) -> None:
    self._export(trainer, state)


class LaggedCheckpointExportHook(CheckpointExportHook):
  """Maintains latest + one-version-lagged export dirs (ref :96-206).

  On each export: the previously-newest version is mirrored into
  ``lagged_export_dir`` BEFORE the new version lands in ``export_dir``, so a
  reader of the lagged dir always sees weights exactly one export behind —
  the reference's filesystem-as-target-network trick for TD3.
  """

  def __init__(self, export_dir: str, lagged_export_dir: str, **kwargs):
    super().__init__(export_dir, **kwargs)
    self._lagged_export_dir = lagged_export_dir

  @property
  def lagged_export_dir(self) -> str:
    return self._lagged_export_dir

  def _mirror_version(self, version_dir: str) -> None:
    """Atomically copies one version dir into the lagged dir (idempotent)."""
    version = os.path.basename(version_dir)
    target = os.path.join(self._lagged_export_dir, version)
    if os.path.isdir(target):
      return
    os.makedirs(self._lagged_export_dir, exist_ok=True)
    tmp = os.path.join(self._lagged_export_dir, 'tmp-' + version)
    shutil.copytree(version_dir, tmp)
    os.rename(tmp, target)  # atomic: pollers never see partials

  def _export(self, trainer, state):
    step = int(jax.device_get(state.step))
    if step == self._last_exported_step:
      # No new export will land (end-of-train dedupe): do NOT advance the
      # lagged dir, or the target network would catch up to the live one.
      return None
    latest = export_generators.list_exported_versions(self._export_dir)
    if latest:
      self._mirror_version(os.path.join(self._export_dir, str(latest[-1])))
      _gc_versions(self._lagged_export_dir, self._exports_to_keep)
    path = super()._export(trainer, state)
    if path is not None and not export_generators.list_exported_versions(
        self._lagged_export_dir):
      # First export ever: seed the lagged dir so TD3 actors can start
      # immediately (ref :96 initial-copy behavior).
      self._mirror_version(path)
    return path
