"""Training hooks: periodic export, lagged target-network dirs, logging."""

from tensor2robot_tpu.hooks.hook_builder import HookBuilder, TrainHook
from tensor2robot_tpu.hooks.checkpoint_hooks import (
    CheckpointExportHook,
    LaggedCheckpointExportHook,
)
from tensor2robot_tpu.hooks.async_export_hook_builder import (
    AsyncExportHookBuilder,
)
from tensor2robot_tpu.hooks.td3 import TD3Hooks
from tensor2robot_tpu.hooks.variable_logger_hook import VariableLoggerHook

__all__ = [
    'AsyncExportHookBuilder',
    'CheckpointExportHook',
    'HookBuilder',
    'LaggedCheckpointExportHook',
    'TD3Hooks',
    'TrainHook',
    'VariableLoggerHook',
]
