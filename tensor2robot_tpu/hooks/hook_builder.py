"""Hook protocol + builder interface.

Parity target: /root/reference/hooks/hook_builder.py:32-48 (HookBuilder
creating SessionRunHooks for the Estimator). Here hooks are plain objects the
Trainer calls around its jitted step loop:

  begin(trainer)                       once, before the first step
  after_step(trainer, state, step, metrics)   every step (metrics may be a
                                       device pytree except on log steps)
  end(trainer, state)                  once, after the last step
"""

from __future__ import annotations

from typing import Any, List, Optional


class TrainHook:
  """No-op base hook; subclasses override what they need."""

  def begin(self, trainer) -> None:
    pass

  def after_step(self, trainer, state, step: int,
                 metrics: Optional[Any]) -> None:
    pass

  def end(self, trainer, state) -> None:
    pass


class HookBuilder:
  """Creates hooks bound to a model + trainer (ref hook_builder.py:32)."""

  def create_hooks(self, t2r_model, trainer) -> List[TrainHook]:
    raise NotImplementedError
