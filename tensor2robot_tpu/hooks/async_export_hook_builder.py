"""AsyncExportHookBuilder: serve-during-training export wiring.

Parity target: /root/reference/hooks/async_export_hook_builder.py:46-138.
The reference pairs a background AsyncCheckpointSaverHook with a
CheckpointExportListener so a SavedModel appears for every checkpoint while
training continues. Here checkpointing is already asynchronous (Orbax, see
trainer/checkpointing.py); this builder contributes the per-interval export
hook writing serving artifacts robot-side predictors poll.
"""

from __future__ import annotations

import os
from typing import List

from tensor2robot_tpu.hooks.checkpoint_hooks import CheckpointExportHook
from tensor2robot_tpu.hooks.hook_builder import HookBuilder, TrainHook

DEFAULT_EXPORT_DIRNAME = os.path.join('export', 'latest_exporter')


class AsyncExportHookBuilder(HookBuilder):
  """Builds the export-per-checkpoint hook (ref :46)."""

  def __init__(self,
               export_dir: str = '',
               save_secs: int = 90,
               save_steps: int = 500,
               exports_to_keep: int = 5,
               export_generator=None):
    """``save_secs`` is accepted for reference-API compatibility; the
    step-driven trainer exports every ``save_steps`` (ref :59 uses secs
    because TF hooks are wall-clock driven)."""
    del save_secs
    self._export_dir = export_dir
    self._save_steps = save_steps
    self._exports_to_keep = exports_to_keep
    self._export_generator = export_generator

  def create_hooks(self, t2r_model, trainer) -> List[TrainHook]:
    del t2r_model
    export_dir = self._export_dir or os.path.join(trainer.model_dir,
                                                  DEFAULT_EXPORT_DIRNAME)
    return [
        CheckpointExportHook(
            export_dir,
            export_every_steps=self._save_steps,
            exports_to_keep=self._exports_to_keep,
            export_generator=self._export_generator)
    ]
