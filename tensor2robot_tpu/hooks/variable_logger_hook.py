"""VariableLoggerHook: periodic parameter statistics logging.

Parity target: /root/reference/hooks/variable_logger_hook.py:33-68 (logs
mean/std/values of every variable per run). One device_get per log interval;
never inside the jitted step.
"""

from __future__ import annotations

import jax
import numpy as np
from absl import logging

from tensor2robot_tpu.hooks.hook_builder import TrainHook


class VariableLoggerHook(TrainHook):
  """Logs per-variable mean/std every ``log_every_n_steps`` steps."""

  def __init__(self, log_every_n_steps: int = 100, log_values: bool = False,
               max_num_variable_values: int = 16):
    self._log_every_n_steps = log_every_n_steps
    self._log_values = log_values
    self._max_num_variable_values = max_num_variable_values
    self._log = logging.info

  def after_step(self, trainer, state, step: int, metrics) -> None:
    if step % self._log_every_n_steps != 0:
      return
    flat, _ = jax.tree_util.tree_flatten_with_path(
        jax.device_get(state.params))
    for path, value in flat:
      name = '/'.join(str(getattr(p, 'key', p)) for p in path)
      value = np.asarray(value)
      self._log('var %s: shape=%s mean=%.6f std=%.6f', name, value.shape,
                float(value.mean()), float(value.std()))
      if self._log_values:
        self._log('var %s values: %s', name,
                  value.ravel()[:self._max_num_variable_values])
