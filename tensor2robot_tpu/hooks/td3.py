"""TD3Hooks: lagged-export wiring for filesystem target networks.

Parity target: /root/reference/hooks/td3.py:40-135 — builds the latest +
lagged export-dir pair (the TD3 target network lives one export behind) and
writes warmup requests into each artifact (the export generator already
bundles spec-conforming warmup features, abstract_export_generator.py:114-147).
"""

from __future__ import annotations

import os
from typing import List

from tensor2robot_tpu.hooks.checkpoint_hooks import LaggedCheckpointExportHook
from tensor2robot_tpu.hooks.hook_builder import HookBuilder, TrainHook


class TD3Hooks(HookBuilder):
  """Latest + lagged serving exports for actor/target decoupling (ref :40)."""

  def __init__(self,
               export_dir: str = '',
               lagged_export_dir: str = '',
               save_steps: int = 500,
               exports_to_keep: int = 5,
               export_generator=None):
    self._export_dir = export_dir
    self._lagged_export_dir = lagged_export_dir
    self._save_steps = save_steps
    self._exports_to_keep = exports_to_keep
    self._export_generator = export_generator

  def create_hooks(self, t2r_model, trainer) -> List[TrainHook]:
    del t2r_model
    export_dir = self._export_dir or os.path.join(
        trainer.model_dir, 'export', 'latest_exporter')
    lagged_dir = self._lagged_export_dir or os.path.join(
        trainer.model_dir, 'export', 'lagged_exporter')
    return [
        LaggedCheckpointExportHook(
            export_dir,
            lagged_dir,
            export_every_steps=self._save_steps,
            exports_to_keep=self._exports_to_keep,
            export_generator=self._export_generator)
    ]
