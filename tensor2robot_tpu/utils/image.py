"""Image encode/decode helpers.

Parity target: /root/reference/utils/image.py (jpeg_string :29,
numpy_to_image_string :49) — numpy image -> encoded bytes for writing
tf.Example replay records.
"""

from __future__ import annotations

import io

import numpy as np
from PIL import Image


def jpeg_string(image: 'Image.Image', jpeg_quality: int = 90) -> bytes:
  """Encodes a PIL image as JPEG bytes (ref image.py:29)."""
  buf = io.BytesIO()
  image.save(buf, format='JPEG', quality=jpeg_quality)
  return buf.getvalue()


def numpy_to_image_string(image_array: np.ndarray,
                          image_format: str = 'jpeg',
                          data_type=np.uint8) -> bytes:
  """Encodes [H, W, C] numpy array to an image byte string (ref :49)."""
  image_array = np.asarray(image_array, dtype=data_type)
  image = Image.fromarray(image_array)
  buf = io.BytesIO()
  image.save(buf, format=image_format.upper())
  return buf.getvalue()


def image_string_to_numpy(image_bytes: bytes) -> np.ndarray:
  """Decodes encoded image bytes back to a numpy array."""
  with io.BytesIO(image_bytes) as buf:
    return np.asarray(Image.open(buf))
