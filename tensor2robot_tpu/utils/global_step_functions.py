"""Hyperparameter schedules as functions of the global step.

Parity target: /root/reference/utils/global_step_functions.py
(piecewise_linear :33, exponential_decay :104) — configurable schedules
for any scalar hyperparameter. These are plain jnp functions so they work
both inside jit (as optax-style schedules) and on the host.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp


def piecewise_linear(boundaries: Sequence[int],
                     values: Sequence[float]):
  """Linear interpolation between (boundary, value) knots (ref :33).

  Before the first boundary the first value holds; after the last, the
  last value holds; in between, linear interpolation.
  """
  if len(boundaries) != len(values):
    raise ValueError(
        'boundaries and values must have equal length; got {} vs {}.'.format(
            len(boundaries), len(values)))
  if list(boundaries) != sorted(boundaries):
    raise ValueError('boundaries must be sorted ascending.')
  boundaries_arr = jnp.asarray(boundaries, jnp.float32)
  values_arr = jnp.asarray(values, jnp.float32)

  def schedule(global_step):
    step = jnp.asarray(global_step, jnp.float32)
    return jnp.interp(step, boundaries_arr, values_arr)

  return schedule


def exponential_decay(initial_value: float = 0.0001,
                      decay_steps: int = 10000,
                      decay_rate: float = 0.9,
                      staircase: bool = True):
  """value * decay_rate^(step/decay_steps) (ref :104)."""

  def schedule(global_step):
    exponent = jnp.asarray(global_step, jnp.float32) / decay_steps
    if staircase:
      exponent = jnp.floor(exponent)
    return initial_value * decay_rate ** exponent

  return schedule
