"""Cross-entropy method (CEM) optimizers.

Parity target: /root/reference/utils/cross_entropy.py (CrossEntropyMethod
:35, NormalCrossEntropyMethod :115), same call contract: sample batches
are lists/arrays or dicts of them, ``sample_fn(**params)``,
``update_fn(params, elites) -> params``. The reference runs CEM in numpy
on the robot host with the Q-network behind a session; here the objective
is typically a jitted batched apply, so a fully device-side
``jax.lax.scan`` variant is also provided (one XLA dispatch per action,
ref §3.5 hot loop).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def cross_entropy_method(sample_fn: Callable,
                         objective_fn: Callable,
                         update_fn: Callable,
                         initial_params: dict,
                         num_elites: int,
                         num_iterations: int = 1,
                         threshold_to_terminate: Optional[float] = None):
  """CEM maximization (ref CrossEntropyMethod :35).

  Args:
    sample_fn: ``sample_fn(**params)`` -> sample batch (list/array of
      samples, or dict mapping keys to lists/arrays).
    objective_fn: sample batch -> list of scalars.
    update_fn: ``update_fn(params, elite_samples)`` -> updated params.
    initial_params: dict of initial sampling parameters.
    num_elites: elites passed to update_fn per iteration.
    num_iterations: iterations to run.
    threshold_to_terminate: early-exit once best value exceeds this.

  Returns:
    (final_samples, final_values, final_params).
  """
  updated_params = initial_params
  samples = values = None
  for _ in range(num_iterations):
    samples = sample_fn(**updated_params)
    values = np.asarray(objective_fn(samples))
    order = np.argsort(values)
    elite_idx = order[-num_elites:]
    if isinstance(samples, dict):
      elite_samples = {
          k: np.asarray(v)[elite_idx] for k, v in samples.items()}
    else:
      elite_samples = np.asarray(samples)[elite_idx]
    updated_params = update_fn(updated_params, elite_samples)
    if (threshold_to_terminate is not None and
        np.max(values) > threshold_to_terminate):
      break
  return samples, values, updated_params


def normal_cross_entropy_method(objective_fn,
                                mean,
                                stddev,
                                num_samples: int,
                                num_elites: int,
                                num_iterations: int = 1):
  """CEM with a normal sampling distribution (ref :115).

  Returns (mean, stddev) of the final sampling distribution.
  """
  size = np.broadcast(np.asarray(mean), np.asarray(stddev)).size

  def sample_fn(mean, stddev):
    return mean + stddev * np.random.randn(num_samples, size)

  def update_fn(params, elite_samples):
    del params
    return {
        'mean': np.mean(elite_samples, axis=0),
        'stddev': np.std(elite_samples, axis=0, ddof=1),  # Bessel
    }

  _, _, final_params = cross_entropy_method(
      sample_fn, objective_fn, update_fn,
      {'mean': mean, 'stddev': stddev}, num_elites,
      num_iterations=num_iterations)
  return final_params['mean'], final_params['stddev']


def jax_normal_cem(objective_fn,
                   mean: jnp.ndarray,
                   stddev: jnp.ndarray,
                   rng: jax.Array,
                   num_samples: int = 64,
                   num_elites: int = 6,
                   num_iterations: int = 3):
  """Device-side CEM: the whole optimize loop is one XLA program.

  ``objective_fn`` must be traceable (e.g. a batched Q apply). Used by the
  serving path so one policy step is a single device dispatch instead of
  ``num_iterations`` host round-trips.

  Returns (mean, stddev, best_sample).
  """

  def body(carry, step_rng):
    mu, sigma = carry
    noise = jax.random.normal(step_rng, (num_samples,) + mu.shape,
                              mu.dtype)
    samples = mu + sigma * noise
    scores = objective_fn(samples)
    _, elite_idx = jax.lax.top_k(scores, num_elites)
    elites = jnp.take(samples, elite_idx, axis=0)
    new_mu = jnp.mean(elites, axis=0)
    new_sigma = jnp.std(elites, axis=0)
    best = elites[0]  # top_k is descending; index 0 is the best sample
    return (new_mu, new_sigma), best

  rngs = jax.random.split(rng, num_iterations)
  (mean, stddev), bests = jax.lax.scan(body, (mean, stddev), rngs)
  return mean, stddev, bests[-1]
