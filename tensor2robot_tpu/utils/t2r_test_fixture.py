"""T2RModelFixture: run any model through the real harness in tests.

Parity target: /root/reference/utils/t2r_test_fixture.py:37 (random_train /
recordio_train / random_predict through the full train_eval_model into a
tempdir, then assert_output_files). Downstream users exercise new models
with two lines instead of bespoke trainer loops:

    fixture = T2RModelFixture(test_case_dir)
    result = fixture.random_train(MyModel(), max_train_steps=2)
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, Optional

import numpy as np

from tensor2robot_tpu.data.input_generators import (
    AbstractInputGenerator,
    DefaultRandomInputGenerator,
    DefaultRecordInputGenerator,
)
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.predictors.checkpoint_predictor import (
    CheckpointPredictor,
)
from tensor2robot_tpu.specs import generators as spec_generators
from tensor2robot_tpu.trainer import checkpointing, train_eval


def assert_output_files(model_dir: str, expect_events: bool = True) -> None:
  """Checkpoints (+ event files) exist (ref train_eval_test_utils.py:37)."""
  step = checkpointing.latest_checkpoint_step(model_dir)
  if step is None:
    raise AssertionError('No checkpoint written under {}.'.format(model_dir))
  if expect_events and not glob.glob(
      os.path.join(model_dir, 'events.out.tfevents.*')):
    raise AssertionError('No event files under {}.'.format(model_dir))
  assets = os.path.join(model_dir, 'assets.extra', 't2r_assets.pbtxt')
  if not os.path.exists(assets):
    raise AssertionError('No t2r_assets written under {}.'.format(model_dir))


class T2RModelFixture:
  """Trains/serves models through the real harness (ref :37)."""

  def __init__(self, base_dir: str, batch_size: int = 8):
    self._base_dir = str(base_dir)
    self._batch_size = batch_size
    self._run_count = 0

  def _next_model_dir(self) -> str:
    self._run_count += 1
    model_dir = os.path.join(self._base_dir, 'run_{}'.format(self._run_count))
    os.makedirs(model_dir, exist_ok=True)
    return model_dir

  def _train(self, t2r_model, input_generator: AbstractInputGenerator,
             max_train_steps: int, model_dir: Optional[str],
             **train_kwargs) -> Dict[str, Any]:
    model_dir = model_dir or self._next_model_dir()
    train_kwargs.setdefault('async_checkpoints', False)
    result = train_eval.train_eval_model(
        t2r_model, model_dir, input_generator_train=input_generator,
        max_train_steps=max_train_steps, **train_kwargs)
    result['model_dir'] = model_dir
    assert_output_files(model_dir,
                        expect_events=train_kwargs.get('write_metrics', True))
    return result

  def random_train(self, t2r_model, max_train_steps: int = 2,
                   model_dir: Optional[str] = None,
                   **train_kwargs) -> Dict[str, Any]:
    """Trains on spec-conforming random data (ref random_train)."""
    generator = DefaultRandomInputGenerator(batch_size=self._batch_size)
    return self._train(t2r_model, generator, max_train_steps, model_dir,
                       **train_kwargs)

  def record_train(self, t2r_model, file_patterns: str,
                   max_train_steps: int = 2,
                   model_dir: Optional[str] = None,
                   **train_kwargs) -> Dict[str, Any]:
    """Trains from TFRecord files (ref recordio_train)."""
    generator = DefaultRecordInputGenerator(file_patterns=file_patterns,
                                            batch_size=self._batch_size)
    return self._train(t2r_model, generator, max_train_steps, model_dir,
                       **train_kwargs)

  def random_predict(self, t2r_model, model_dir: str,
                     batch_size: int = 1) -> Dict[str, np.ndarray]:
    """Restores the newest checkpoint and serves one random batch."""
    predictor = CheckpointPredictor(t2r_model, model_dir, timeout=10.0)
    try:
      if not predictor.restore():
        raise AssertionError(
            'No checkpoint to restore under {}.'.format(model_dir))
      feature_spec = t2r_model.preprocessor.get_in_feature_specification(
          ModeKeys.PREDICT)
      features = spec_generators.make_random_numpy(
          feature_spec, batch_size=batch_size)
      return predictor.predict(features.to_dict())
    finally:
      predictor.close()

  def restore_predict_parity(self, make_model, model_dir: str,
                             batch_size: int = 1,
                             rtol: float = 1e-5) -> None:
    """Two fresh restores produce identical predictions (serve determinism)."""
    features = None
    outputs = []
    for _ in range(2):
      model = make_model()
      predictor = CheckpointPredictor(model, model_dir, timeout=10.0)
      try:
        assert predictor.restore()
        if features is None:
          feature_spec = model.preprocessor.get_in_feature_specification(
              ModeKeys.PREDICT)
          features = spec_generators.make_random_numpy(
              feature_spec, batch_size=batch_size, seed=7).to_dict()
        outputs.append(predictor.predict(features))
      finally:
        predictor.close()
    for key in outputs[0]:
      np.testing.assert_allclose(outputs[0][key], outputs[1][key], rtol=rtol,
                                 err_msg='mismatch for {}'.format(key))
