"""Mock model + input generator: the backbone of the test strategy.

Parity target: /root/reference/utils/mocks.py (MockT2RModel :104 — a 3-layer
MLP with batch norm over an 8-dim state; MockInputGenerator :48 — a
deterministic linearly separable dataset, seed=1234).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.data.input_generators import GeneratorInputGenerator
from tensor2robot_tpu.models.classification_model import ClassificationModel
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec

MOCK_STATE_DIM = 8


class _MockNetwork(nn.Module):
  """3-layer MLP with batch norm (ref mocks.py:104)."""

  use_batch_norm: bool = True

  @nn.compact
  def __call__(self, features, mode: str = 'train', train: bool = False):
    x = jnp.asarray(features['measured_position'], jnp.float32)
    for width in (100, 100):
      x = nn.Dense(width)(x)
      if self.use_batch_norm:
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
      x = nn.relu(x)
    logits = nn.Dense(1)(x)
    return {'logits': logits}


class MockT2RModel(ClassificationModel):
  """Tiny classification model over an 8-dim state vector."""

  def __init__(self, use_batch_norm: bool = True, **kwargs):
    kwargs.setdefault('device_type', 'cpu')
    super().__init__(**kwargs)
    self._use_batch_norm = use_batch_norm

  def get_feature_specification(self, mode: str) -> SpecStruct:
    return SpecStruct(measured_position=TensorSpec(
        (MOCK_STATE_DIM,), np.float32, name='measured_position'))

  def get_label_specification(self, mode: str) -> SpecStruct:
    return SpecStruct(target=TensorSpec((1,), np.float32, name='valid_position'))

  def create_network(self) -> nn.Module:
    return _MockNetwork(use_batch_norm=self._use_batch_norm)


class MockInputGenerator(GeneratorInputGenerator):
  """Deterministic linearly separable batches (ref mocks.py:48)."""

  def __init__(self, seed: int = 1234, **kwargs):
    super().__init__(**kwargs)
    self._rng = np.random.RandomState(seed)

  def _generate_batch(self, seed: Optional[int]):
    # Honor the per-batch seed contract for reproducible replay; fall back
    # to the stateful stream when unseeded.
    rng = self._rng if seed is None else np.random.RandomState(seed)
    states = rng.rand(self._batch_size, MOCK_STATE_DIM).astype(np.float32)
    # Linearly separable rule: positive iff mean(state) > 0.5.
    labels = (states.mean(axis=1, keepdims=True) > 0.5).astype(np.float32)
    features = SpecStruct(measured_position=states)
    label_struct = SpecStruct(target=labels)
    return features, label_struct
