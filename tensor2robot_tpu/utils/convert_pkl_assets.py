"""Migrate reference pickle-based assets to ``t2r_assets.pbtxt``.

Parity: the reference's one-shot migration CLI
(``utils/convert_pkl_assets_to_proto_assets.py:40`` ``convert()``) and the
pickle writers it retires (``utils/tensorspec_utils.py:1698-1729``
``write_input_spec_to_file`` / ``write_global_step_to_file``).

The reference unpickles by importing its live TF1 classes. Here a
*restricted* unpickler rebuilds our :class:`TensorSpec` / :class:`SpecStruct`
directly from the opcode stream instead, so asset directories written by the
reference (``input_specs.pkl`` + optional ``global_step.pkl``) migrate

* without TF1 or the ``tensor2robot`` package installed, and
* without executing arbitrary pickle globals — only an allowlist of
  spec/shape/dtype constructors resolves; anything else raises
  ``pickle.UnpicklingError`` naming the offending global.

The allowlist covers exactly what the reference's writers can emit: its
``ExtendedTensorSpec`` (pickled via ``__reduce__`` as a 9-tuple of
constructor args — ``utils/tensorspec_utils.py:278``), its
``TensorSpecStruct`` (an OrderedDict subclass with flat ``a/b`` paths),
plain ``tf.TensorSpec``, and TF's ``TensorShape``/``Dimension``/``as_dtype``
reduction hooks.
"""

import collections
import io
import os
import pickle
from typing import Any, Optional, Tuple

from tensor2robot_tpu.specs.assets import T2R_ASSETS_FILENAME
from tensor2robot_tpu.specs.assets import write_t2r_assets_to_file
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec


# -- Shims the restricted unpickler substitutes for reference globals -------


def _tensor_shape(dims=None) -> Tuple[Optional[int], ...]:
  """tf.TensorShape reduces to (TensorShape, ([Dimension...],))."""
  if dims is None:
    return ()
  return tuple(dims)


def _dimension(value=None) -> Optional[int]:
  """tf Dimension(v) -> the plain int (or None for unknown)."""
  return None if value is None else int(value)


def _as_dtype(name):
  """tf dtypes reduce to (as_dtype, ('float32',)); keep the name string.

  Our ``TensorSpec`` constructor canonicalizes dtype names itself
  (``specs/tensor_spec.py`` ``canonical_dtype``), so the shim only has to
  carry the name through the pickle graph.
  """
  return name


def _extended_tensor_spec(shape, dtype, name=None, is_optional=None,
                          is_sequence=False, is_extracted=False,
                          data_format=None, dataset_key=None,
                          varlen_default_value=None) -> TensorSpec:
  """The reference ExtendedTensorSpec __reduce__ arg order, verbatim."""
  return TensorSpec(
      shape=_tensor_shape(shape) if not isinstance(shape, tuple) else shape,
      dtype=dtype, name=name, is_optional=is_optional,
      is_sequence=is_sequence, is_extracted=is_extracted,
      data_format=data_format, dataset_key=dataset_key,
      varlen_default_value=varlen_default_value)


def _plain_tensor_spec(shape, dtype, name=None) -> TensorSpec:
  return _extended_tensor_spec(shape, dtype, name)


class _SpecStructShim(collections.OrderedDict):
  """Stand-in for the reference TensorSpecStruct during unpickling.

  OrderedDict subclasses pickle as ``cls()`` + SETITEMS + an instance-dict
  BUILD; the reference class keeps internal attributes in ``__dict__`` that
  have no meaning here, so the state is dropped.
  """

  def __setstate__(self, state):  # noqa: ARG002 - reference-internal state
    pass


_ALLOWED_GLOBALS = {
    ('collections', 'OrderedDict'): collections.OrderedDict,
    ('tensor2robot.utils.tensorspec_utils', 'ExtendedTensorSpec'):
        _extended_tensor_spec,
    ('tensor2robot.utils.tensorspec_utils', 'TensorSpecStruct'):
        _SpecStructShim,
    ('tensorflow.python.framework.tensor_shape', 'TensorShape'):
        _tensor_shape,
    ('tensorflow.python.framework.tensor_shape', 'Dimension'): _dimension,
    ('tensorflow.python.framework.tensor_shape', 'as_dimension'): _dimension,
    ('tensorflow.python.framework.dtypes', 'as_dtype'): _as_dtype,
    ('tensorflow.python.framework.tensor_spec', 'TensorSpec'):
        _plain_tensor_spec,
}


class _RestrictedUnpickler(pickle.Unpickler):

  def find_class(self, module: str, name: str):
    try:
      return _ALLOWED_GLOBALS[(module, name)]
    except KeyError:
      raise pickle.UnpicklingError(
          'Refusing to resolve pickle global {}.{} — only reference '
          'tensorspec assets can be converted (allowed: {}).'.format(
              module, name,
              sorted('{}.{}'.format(m, n) for m, n in _ALLOWED_GLOBALS)))


def _restricted_load(data: bytes) -> Any:
  return _RestrictedUnpickler(io.BytesIO(data)).load()


# -- Public API --------------------------------------------------------------


def _to_spec_struct(obj: Any) -> SpecStruct:
  """Reference spec containers (TensorSpecStruct / dicts) -> our SpecStruct."""
  if isinstance(obj, TensorSpec):
    # A bare spec pickled at top level; wrap it like the reference's
    # flatten would (single anonymous path).
    return SpecStruct(**{obj.name or 'value': obj})
  if isinstance(obj, collections.abc.Mapping):
    # TensorSpecStruct keys are flat 'a/b' paths; SpecStruct.__setitem__
    # accepts the same path syntax and splices nested mappings itself.
    out = SpecStruct()
    for key, value in obj.items():
      out[key] = value
    return out
  raise ValueError(
      'Unsupported pickled spec container: {!r}'.format(type(obj)))


def load_input_spec_from_pkl(filename: str):
  """Reads a reference ``input_specs.pkl`` -> (feature_spec, label_spec).

  Mirrors ``load_input_spec_from_file`` (ref tensorspec_utils.py:1705):
  the payload is ``{'in_feature_spec': ..., 'in_label_spec': ...}``.
  """
  with open(filename, 'rb') as f:
    payload = _restricted_load(f.read())
  if not isinstance(payload, collections.abc.Mapping) or not (
      'in_feature_spec' in payload and 'in_label_spec' in payload):
    raise ValueError(
        '{} is not a reference input_specs.pkl (expected in_feature_spec/'
        'in_label_spec keys, got {!r}).'.format(
            filename, sorted(payload) if isinstance(
                payload, collections.abc.Mapping) else type(payload)))
  return (_to_spec_struct(payload['in_feature_spec']),
          _to_spec_struct(payload['in_label_spec']))


def load_global_step_from_pkl(filename: str) -> int:
  """Reads a reference ``global_step.pkl`` (ref tensorspec_utils.py:1721)."""
  with open(filename, 'rb') as f:
    payload = _restricted_load(f.read())
  return int(payload['global_step'])


def convert(assets_filepath: str) -> str:
  """Converts a reference pickle asset dir to ``t2r_assets.pbtxt``.

  Same contract as the reference ``convert()``
  (convert_pkl_assets_to_proto_assets.py:40): ``input_specs.pkl`` is
  required, ``global_step.pkl`` optional, and the output lands next to
  them. Returns the written pbtxt path.
  """
  input_spec_filepath = os.path.join(assets_filepath, 'input_specs.pkl')
  if not os.path.exists(input_spec_filepath):
    raise ValueError('No file exists for {}.'.format(input_spec_filepath))
  feature_spec, label_spec = load_input_spec_from_pkl(input_spec_filepath)

  global_step = None
  global_step_filepath = os.path.join(assets_filepath, 'global_step.pkl')
  if os.path.exists(global_step_filepath):
    global_step = load_global_step_from_pkl(global_step_filepath)

  out_path = os.path.join(assets_filepath, T2R_ASSETS_FILENAME)
  write_t2r_assets_to_file(feature_spec, label_spec, global_step, out_path)
  return out_path


def main(argv=None) -> None:
  import argparse
  parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
  parser.add_argument('--assets_filepath', required=True,
                      help='Exported savedmodel assets directory holding '
                           'input_specs.pkl (+ optional global_step.pkl).')
  args = parser.parse_args(argv)
  print(convert(args.assets_filepath))


if __name__ == '__main__':
  main()
