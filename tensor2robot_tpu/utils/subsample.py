"""Fixed-length subsampling of variable-length sequences.

Parity target: /root/reference/utils/subsample.py (get_subsample_indices
:25, randomized-boundary variant :84, numpy variant :162): pick
``sequence_length`` frames from an episode of ``len`` steps, always
including the first and last frame, evenly spaced (optionally with random
jitter inside each span).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def get_subsample_indices_numpy(sequence_lengths: np.ndarray,
                                sequence_length: int,
                                rng: Optional[np.random.RandomState] = None,
                                randomized: bool = False) -> np.ndarray:
  """[batch] episode lengths -> [batch, sequence_length] frame indices."""
  sequence_lengths = np.asarray(sequence_lengths)
  batch = sequence_lengths.shape[0]
  out = np.zeros((batch, sequence_length), np.int64)
  rng = rng or np.random.RandomState()
  for i, length in enumerate(sequence_lengths):
    out[i] = _single_subsample_numpy(int(length), sequence_length,
                                     rng if randomized else None)
  return out


def _single_subsample_numpy(length: int, k: int,
                            rng: Optional[np.random.RandomState]
                            ) -> np.ndarray:
  if length <= k:
    # Short episodes: keep everything, pad by repeating the last frame.
    idx = np.arange(k)
    return np.minimum(idx, max(length - 1, 0))
  # k spans over [0, length); first index 0, last index length-1.
  boundaries = np.linspace(0, length - 1, k)
  if rng is None:
    return np.round(boundaries).astype(np.int64)
  # Randomized: jitter each midpoint within its span, keep endpoints.
  low = np.floor(np.linspace(0, length - 1, k + 1)[:-1])
  high = np.ceil(np.linspace(0, length - 1, k + 1)[1:])
  picks = np.array([rng.randint(int(l), max(int(h), int(l) + 1))
                    for l, h in zip(low, high)], np.int64)
  picks[0] = 0
  picks[-1] = length - 1
  return np.clip(picks, 0, length - 1)


def get_subsample_indices(sequence_lengths: jnp.ndarray,
                          sequence_length: int,
                          rng: Optional[jax.Array] = None) -> jnp.ndarray:
  """JAX variant: static output shape, traceable under jit.

  Randomization is enabled by passing ``rng``.
  """
  sequence_lengths = jnp.asarray(sequence_lengths)

  def one(length, key):
    length = jnp.maximum(length, 1)
    positions = jnp.linspace(0.0, 1.0, sequence_length)
    base = positions * (length - 1).astype(jnp.float32)
    if key is not None:
      span = (length - 1).astype(jnp.float32) / jnp.maximum(
          sequence_length - 1, 1)
      jitter = (jax.random.uniform(key, (sequence_length,)) - 0.5) * span
      # Endpoints stay pinned to first/last frame.
      jitter = jitter.at[0].set(0.0).at[-1].set(0.0)
      base = base + jitter
    idx = jnp.clip(jnp.round(base).astype(jnp.int32), 0, length - 1)
    # Short episodes: match the numpy variant exactly — keep every frame,
    # pad by repeating the last one (not a rounded resample).
    short = jnp.minimum(jnp.arange(sequence_length), length - 1)
    return jnp.where(length <= sequence_length, short, idx)

  if rng is None:
    return jax.vmap(lambda l: one(l, None))(sequence_lengths)
  keys = jax.random.split(rng, sequence_lengths.shape[0])
  return jax.vmap(one)(sequence_lengths, keys)


def subsample_sequence(tensor, indices):
  """Gathers [batch, time, ...] frames by per-batch [batch, k] indices."""
  if isinstance(tensor, np.ndarray):
    return np.stack([tensor[i, indices[i]] for i in range(tensor.shape[0])])
  return jax.vmap(lambda x, idx: jnp.take(x, idx, axis=0))(tensor, indices)
