"""Utilities: mocks, fixtures, subsampling, schedules, CEM, image helpers."""
