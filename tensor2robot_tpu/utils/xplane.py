"""Dependency-free xplane.pb reader: per-op device-time attribution.

``jax.profiler.trace`` writes TensorBoard xplane protos, but this image
(and many serving hosts) carries no profiler proto bindings — so the
round-5 headline-tail attribution (docs/performance.md) walks the wire
format directly, on the SAME protobuf-free primitives the framework's
tf.Example codec uses (`data/wire.py` `_iter_fields`, which raises on
malformed varints and unsupported wire types, so truncated or corrupt
captures fail loudly instead of desynchronizing into garbage totals).

Wire schema subset (tensorflow/tsl profiler xplane.proto):

    XSpace  { repeated XPlane planes = 1; }
    XPlane  { string name = 2; repeated XLine lines = 3;
              map<int64, XEventMetadata> event_metadata = 4; }
    XLine   { string name = 2; repeated XEvent events = 4; }
    XEvent  { int64 metadata_id = 1; int64 offset_ps = 2;
              int64 duration_ps = 3; }
    XEventMetadata { string name = 2; }

Typical use::

    jax.profiler.start_trace(logdir); ...steps...; jax.profiler.stop_trace()
    path = glob.glob(logdir + '/**/*.xplane.pb', recursive=True)[0]
    for name, ms in op_families(path, n_steps=3)[:20]:
        print(name, ms)

Caveats: summing a line's events assumes the line is a serial stream —
true for the TensorCore ``XLA Ops`` line; the ``Async XLA Ops`` line
holds overlapping DMA windows and must not be summed as wall time. The
aggregators operate on exactly ONE plane and raise when ``plane_substr``
matches several (a multi-chip capture has one TPU plane per chip;
summing across them would multiply ms/step by the chip count).
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Tuple

# Shared protobuf wire walker (loud on malformed input) — the one place
# varint/field framing is implemented in this codebase.
from tensor2robot_tpu.data.wire import _iter_fields

_WIRE_VARINT = 0
_WIRE_BYTES = 2


def _parse_event(buf, start, end) -> Tuple[int, int, int]:
  metadata_id = offset_ps = duration_ps = 0
  for field, wire, value in _iter_fields(buf, start, end):
    if field == 1 and wire == _WIRE_VARINT:
      metadata_id = value
    elif field == 2 and wire == _WIRE_VARINT:
      offset_ps = value
    elif field == 3 and wire == _WIRE_VARINT:
      duration_ps = value
  return metadata_id, duration_ps, offset_ps


def _parse_line(buf, start, end):
  name = ''
  events: List[Tuple[int, int, int]] = []
  for field, wire, value in _iter_fields(buf, start, end):
    if field == 2 and wire == _WIRE_BYTES:
      name = bytes(buf[value[0]:value[1]]).decode('utf-8', 'replace')
    elif field == 4 and wire == _WIRE_BYTES:
      events.append(_parse_event(buf, *value))
  return name, events


def _parse_metadata_entry(buf, start, end) -> Tuple[int, str]:
  key = 0
  name = ''
  for field, wire, value in _iter_fields(buf, start, end):
    if field == 1 and wire == _WIRE_VARINT:
      key = value
    elif field == 2 and wire == _WIRE_BYTES:
      for f2, w2, v2 in _iter_fields(buf, *value):
        if f2 == 2 and w2 == _WIRE_BYTES:
          name = bytes(buf[v2[0]:v2[1]]).decode('utf-8', 'replace')
  return key, name


def _parse_plane(buf, start, end):
  name = ''
  lines = []
  metadata: Dict[int, str] = {}
  for field, wire, value in _iter_fields(buf, start, end):
    if field == 2 and wire == _WIRE_BYTES:
      name = bytes(buf[value[0]:value[1]]).decode('utf-8', 'replace')
    elif field == 3 and wire == _WIRE_BYTES:
      lines.append(_parse_line(buf, *value))
    elif field == 4 and wire == _WIRE_BYTES:
      key, meta_name = _parse_metadata_entry(buf, *value)
      metadata[key] = meta_name
  return name, lines, metadata


def parse_xspace(path: str):
  """[(plane_name, [(line_name, [(metadata_id, duration_ps,
  offset_ps)])], meta)]."""
  with open(path, 'rb') as f:
    buf = f.read()
  planes = []
  for field, wire, value in _iter_fields(buf, 0, len(buf)):
    if field == 1 and wire == _WIRE_BYTES:
      planes.append(_parse_plane(buf, *value))
  return planes


def op_totals(path: str,
              n_steps: int = 1,
              plane_substr: str = 'TPU',
              line_name: str = 'XLA Ops') -> Dict[str, float]:
  """{full op name: ms per step} over ONE plane's selected serial line.

  Raises when ``plane_substr`` is ambiguous (several matching planes
  with that line — e.g. one per chip on a multi-chip capture): summing
  across chips would report chip_count x the per-chip step time.
  """
  matches = []
  for name, lines, metadata in parse_xspace(path):
    if plane_substr not in name:
      continue
    totals: Dict[str, float] = {}
    for lname, events in lines:
      if lname != line_name:
        continue
      for metadata_id, duration_ps, _ in events:
        key = metadata.get(metadata_id, str(metadata_id))
        totals[key] = totals.get(key, 0.0) + duration_ps / 1e9 / n_steps
    if totals:
      matches.append((name, totals))
  if len(matches) > 1:
    raise ValueError(
        'plane_substr {!r} matches {} planes with a {!r} line ({}); '
        'narrow it to one device (e.g. "/device:TPU:0").'.format(
            plane_substr, len(matches), line_name,
            [name for name, _ in matches]))
  return matches[0][1] if matches else {}


def line_stats(path: str) -> List[Dict[str, object]]:
  """Per-line busy/extent/occupancy digest for every plane in a capture.

  For each (plane, line) with at least one event::

      {'plane': str, 'line': str, 'events': int,
       'busy_ms':   sum of event durations,
       'extent_ms': max(offset+duration) - min(offset),
       'occupancy': busy_ms / extent_ms (0.0 when the extent is empty)}

  ``occupancy`` is only meaningful for SERIAL lines (the TensorCore
  ``XLA Ops`` line, a CPU executor thread): there it is the fraction of
  the line's active window the device/thread was busy — the idle-gap
  complement is what host-side stalls look like from the device.
  Nested/overlapping lines (the host ``python`` line holds enclosing
  TraceMes) can exceed 1.0; report, don't assert, on those.
  """
  out: List[Dict[str, object]] = []
  for plane_name, lines, _ in parse_xspace(path):
    for line_name, events in lines:
      if not events:
        continue
      busy_ps = 0
      lo = math.inf
      hi = -math.inf
      for _, duration_ps, offset_ps in events:
        busy_ps += duration_ps
        if offset_ps < lo:
          lo = offset_ps
        if offset_ps + duration_ps > hi:
          hi = offset_ps + duration_ps
      extent_ps = max(hi - lo, 0)
      out.append({
          'plane': plane_name,
          'line': line_name,
          'events': len(events),
          'busy_ms': busy_ps / 1e9,
          'extent_ms': extent_ps / 1e9,
          'occupancy': (busy_ps / extent_ps) if extent_ps else 0.0,
      })
  return out


_FAMILY_RE = re.compile(r'\.\d+$')


def op_families(path: str, n_steps: int = 1,
                plane_substr: str = 'TPU',
                line_name: str = 'XLA Ops'
                ) -> List[Tuple[str, float]]:
  """[(op family, ms/step)] descending — '%fusion.12' folds to '%fusion'."""
  families: Dict[str, float] = {}
  for key, ms in op_totals(path, n_steps, plane_substr, line_name).items():
    fam = _FAMILY_RE.sub('', key.split(' = ')[0])
    families[fam] = families.get(fam, 0.0) + ms
  return sorted(families.items(), key=lambda kv: -kv[1])
