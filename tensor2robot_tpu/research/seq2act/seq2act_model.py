"""RT-1-style transformer behavior cloning: episodes of frames -> actions.

BASELINE.json config #5 ("stretch T2RModel to seq-to-action") — the one
workload family the reference never had. Its sequence models collapse each
frame to one vector and run a TCN/attention hybrid over tiny windows
(SNAIL, /root/reference/layers/snail.py:78); this model keeps K visual
tokens per frame (conv stem + TokenLearner) and runs a causal transformer
over the full episode's token sequence, with the attention backend scaling
from dense XLA through the Pallas flash kernel to mesh-sharded ring
attention for long-context episodes (layers/transformer.py).

Actions are discretized per dimension into ``vocab_size`` bins and trained
with cross-entropy (the RT-1 recipe; head shared with the vrgripper
discrete decoder, research/vrgripper/decoders.py:107-139). Serving emits
both the per-step action sequence and the final step's action for
robot-time policies.

Episode data layout follows the framework's episode convention
(vrgripper_env_models.py): fixed ``episode_length`` leading time dim per
example, frames stored as uint8 at source resolution, SequenceExample or
fixed-shape Example records both parse into it.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import transformer as transformer_lib
from tensor2robot_tpu.meta_learning.meta_data import multi_batch_apply
from tensor2robot_tpu.preprocessors import image_transformations
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.models import optimizers as opt_lib
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.research.vrgripper import decoders
from tensor2robot_tpu.specs import algebra
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec


class Seq2ActPreprocessor(AbstractPreprocessor):
  """uint8 episode frames at source res -> cropped float32 in [0, 1].

  Train mode random-crops with one offset per episode (fixed camera; the
  crop must not jitter within an episode — vrgripper preprocessor parity);
  eval/predict center-crops. Runs inside the jitted step.
  """

  def __init__(self,
               model_feature_specification_fn=None,
               model_label_specification_fn=None,
               src_img_res: Tuple[int, int] = (136, 168)):
    super().__init__(model_feature_specification_fn,
                     model_label_specification_fn)
    self._src_img_res = tuple(src_img_res)

  def get_in_feature_specification(self, mode: str) -> SpecStruct:
    spec = algebra.flatten_spec_structure(
        self._model_feature_specification(mode))
    out = SpecStruct()
    for key in spec:
      if key == 'image' or key.endswith('/image'):
        shape = list(spec[key].shape)
        shape[-3:-1] = self._src_img_res
        out[key] = TensorSpec.from_spec(spec[key], shape=tuple(shape),
                                        dtype=np.uint8)
      else:
        out[key] = spec[key]
    return out

  def get_in_label_specification(self, mode: str) -> SpecStruct:
    return algebra.flatten_spec_structure(
        self._model_label_specification(mode))

  def get_out_feature_specification(self, mode: str) -> SpecStruct:
    return algebra.flatten_spec_structure(
        self._model_feature_specification(mode))

  def get_out_label_specification(self, mode: str) -> SpecStruct:
    return algebra.flatten_spec_structure(
        self._model_label_specification(mode))

  def _preprocess_fn(self, features, labels, mode: str, rng=None):
    out_spec = self.get_out_feature_specification(mode)
    # ONE crop key for every */image view: multi-camera views of the same
    # episode must stay registered (the crop-alignment invariant of
    # image_transformations.random_crop_images).
    kcrop = None
    if mode == ModeKeys.TRAIN and rng is not None:
      kcrop = jax.random.split(jnp.asarray(rng))[1]
    for key in features:
      if not (key == 'image' or key.endswith('/image')):
        continue
      images = jnp.asarray(features[key])
      squeeze = images.ndim == 4  # unbatched single episode
      if squeeze:
        images = images[None]
      target_hw = tuple(out_spec[key].shape[-3:-1])
      if target_hw != tuple(images.shape[2:4]):
        if mode == ModeKeys.TRAIN:
          if kcrop is None:
            raise ValueError('TRAIN-mode preprocessing requires an rng key.')
          images = image_transformations.random_crop_episodes(
              kcrop, images, target_hw)
        else:
          images = image_transformations.center_crop_episodes(
              images, target_hw)
      images = jnp.asarray(images, jnp.float32) / 255.0
      features[key] = images[0] if squeeze else images
    return features, labels


class RT1StyleNet(nn.Module):
  """Tokenize frames -> causal transformer -> per-step binned action head."""

  action_size: int
  vocab_size: int
  tokens_per_frame: int
  embed_dim: int
  num_layers: int
  num_heads: int
  head_dim: int
  mlp_dim: int
  max_episode_length: int
  tokenizer_widths: tuple
  attention_mode: str = 'auto'
  mesh: Optional[object] = None
  tp_axis: Optional[str] = None
  moe_experts: int = 0
  moe_top_k: int = 2
  moe_capacity_factor: float = 1.25
  ep_axis: Optional[str] = None
  pipe_axis: Optional[str] = None
  pipeline_microbatches: int = 2
  pipeline_remat: bool = False
  dropout_rate: float = 0.0
  dtype: jnp.dtype = jnp.float32
  use_state_input: bool = False
  num_task_embeddings: int = 0

  @nn.compact
  def __call__(self, features, mode: str = ModeKeys.TRAIN,
               train: bool = False):
    images = jnp.asarray(features['image'], self.dtype)
    b, t = images.shape[0], images.shape[1]

    def _tokenize(frames):
      return transformer_lib.ImageTokenizer(
          num_tokens=self.tokens_per_frame, embed_dim=self.embed_dim,
          widths=self.tokenizer_widths, dtype=self.dtype,
          name='tokenizer')(frames, train=train)

    tokens = multi_batch_apply(_tokenize, 2, images)    # [B, T, K, D]
    k = tokens.shape[2]
    if self.num_task_embeddings:
      # Task conditioning (RT-1's instruction-conditioning analog at the
      # scale this environment permits): a learned per-task embedding
      # token joins every frame's token group.
      # Clamp explicitly: under jit an out-of-range id cannot raise, and
      # relying on the gather's implicit clamp would hide the policy.
      # Host-side entry points (pack_features) validate the range eagerly.
      task_id = jnp.clip(
          jnp.asarray(features['task_id'], jnp.int32).reshape(b), 0,
          self.num_task_embeddings - 1)
      task_token = nn.Embed(self.num_task_embeddings, self.embed_dim,
                            dtype=self.dtype, name='task_embedding')(
                                task_id)                  # [B, D]
      task_token = jnp.broadcast_to(task_token[:, None, None, :],
                                    (b, t, 1, self.embed_dim))
      tokens = jnp.concatenate([tokens, task_token], axis=2)
      k += 1
    if self.use_state_input:
      state = jnp.asarray(features['state'], self.dtype)  # [B, T, S]
      state_token = nn.Dense(self.embed_dim, dtype=self.dtype,
                             name='state_token')(state)[:, :, None, :]
      tokens = jnp.concatenate([tokens, state_token], axis=2)
      k += 1
    tokens = tokens.reshape(b, t * k, self.embed_dim)
    encoded, moe_aux = transformer_lib.CausalTransformer(
        num_layers=self.num_layers, num_heads=self.num_heads,
        head_dim=self.head_dim, mlp_dim=self.mlp_dim,
        max_length=self.max_episode_length * k,
        attention_mode=self.attention_mode, mesh=self.mesh,
        tp_axis=self.tp_axis, moe_experts=self.moe_experts,
        moe_top_k=self.moe_top_k,
        moe_capacity_factor=self.moe_capacity_factor, ep_axis=self.ep_axis,
        pipe_axis=self.pipe_axis,
        pipeline_microbatches=self.pipeline_microbatches,
        pipeline_remat=self.pipeline_remat,
        dropout_rate=self.dropout_rate,
        dtype=self.dtype, name='transformer')(tokens, train=train)
    # Last token of each frame: under the token-causal mask it has seen the
    # whole frame plus all history — the natural readout position.
    frame_out = encoded.reshape(b, t, k, -1)[:, :, -1, :]
    logits = nn.Dense(self.action_size * self.vocab_size, name='action_head',
                      dtype=jnp.float32)(frame_out)  # [B, T, A*V]
    outputs = SpecStruct(action_logits=logits)
    if self.moe_experts:
      outputs['moe_aux_loss'] = moe_aux
    return outputs


class Seq2ActBCModel(AbstractT2RModel):
  """T2R contract around RT1StyleNet (see module docstring)."""

  label_key = 'action'

  def __init__(self,
               episode_length: int = 6,
               action_size: int = 7,
               vocab_size: int = 256,
               img_res: Tuple[int, int] = (128, 160),
               src_img_res: Tuple[int, int] = (136, 168),
               tokens_per_frame: int = 8,
               embed_dim: int = 512,
               num_layers: int = 8,
               num_heads: int = 8,
               head_dim: int = 64,
               mlp_dim: int = 2048,
               tokenizer_widths: Sequence[int] = (32, 64, 128, 256),
               action_min: float = -1.0,
               action_max: float = 1.0,
               attention_mode: str = 'auto',
               mesh: Optional[object] = None,
               tp_axis: Optional[str] = None,
               moe_experts: int = 0,
               moe_top_k: int = 2,
               moe_capacity_factor: float = 1.25,
               ep_axis: Optional[str] = None,
               moe_aux_weight: float = 0.01,
               pipe_axis: Optional[str] = None,
               pipeline_microbatches: int = 2,
               pipeline_remat: bool = False,
               max_episode_length: Optional[int] = None,
               dropout_rate: float = 0.0,
               use_state_input: bool = False,
               state_size: int = 7,
               num_task_embeddings: int = 0,
               learning_rate: float = 1e-4,
               **kwargs):
    import functools
    kwargs.setdefault('device_type', 'cpu')
    kwargs.setdefault(
        'create_optimizer_fn',
        lambda: opt_lib.create_adam_optimizer(learning_rate=learning_rate))
    super().__init__(
        preprocessor_cls=functools.partial(Seq2ActPreprocessor,
                                           src_img_res=tuple(src_img_res)),
        **kwargs)
    self._episode_length = episode_length
    self._action_size = action_size
    self._vocab_size = vocab_size
    self._img_res = tuple(img_res)
    self._src_img_res = tuple(src_img_res)
    self._tokens_per_frame = tokens_per_frame
    self._embed_dim = embed_dim
    self._num_layers = num_layers
    self._num_heads = num_heads
    self._head_dim = head_dim
    self._mlp_dim = mlp_dim
    self._tokenizer_widths = tuple(tokenizer_widths)
    self._action_min = action_min
    self._action_max = action_max
    self._attention_mode = attention_mode
    self._mesh = mesh
    self._tp_axis = tp_axis
    self._moe_experts = moe_experts
    self._moe_top_k = moe_top_k
    self._moe_capacity_factor = moe_capacity_factor
    self._ep_axis = ep_axis
    self._moe_aux_weight = moe_aux_weight
    self._pipe_axis = pipe_axis
    self._pipeline_microbatches = pipeline_microbatches
    self._pipeline_remat = pipeline_remat
    self._max_episode_length = max_episode_length or episode_length
    self._dropout_rate = dropout_rate
    self._use_state_input = use_state_input
    self._state_size = state_size
    self._num_task_embeddings = num_task_embeddings
    self._bin_centers = decoders.get_discrete_bins(
        vocab_size, np.full((action_size,), action_min, np.float32),
        np.full((action_size,), action_max, np.float32))

  @property
  def episode_length(self) -> int:
    return self._episode_length

  def get_feature_specification(self, mode: str) -> SpecStruct:
    del mode
    h, w = self._img_res
    spec = SpecStruct(
        image=TensorSpec((self._episode_length, h, w, 3), np.float32,
                         name='image0', data_format='jpeg'))
    if self._use_state_input:
      spec['state'] = TensorSpec(
          (self._episode_length, self._state_size), np.float32, name='state')
    if self._num_task_embeddings:
      spec['task_id'] = TensorSpec((1,), np.int32, name='task_id')
    return spec

  def get_label_specification(self, mode: str) -> SpecStruct:
    del mode
    return SpecStruct(action=TensorSpec(
        (self._episode_length, self._action_size), np.float32, name='action'))

  def create_network(self) -> nn.Module:
    return RT1StyleNet(
        action_size=self._action_size,
        vocab_size=self._vocab_size,
        tokens_per_frame=self._tokens_per_frame,
        embed_dim=self._embed_dim,
        num_layers=self._num_layers,
        num_heads=self._num_heads,
        head_dim=self._head_dim,
        mlp_dim=self._mlp_dim,
        max_episode_length=self._max_episode_length,
        tokenizer_widths=self._tokenizer_widths,
        attention_mode=self._attention_mode,
        mesh=self._mesh,
        tp_axis=self._tp_axis,
        moe_experts=self._moe_experts,
        moe_top_k=self._moe_top_k,
        moe_capacity_factor=self._moe_capacity_factor,
        ep_axis=self._ep_axis,
        pipe_axis=self._pipe_axis,
        pipeline_microbatches=self._pipeline_microbatches,
        pipeline_remat=self._pipeline_remat,
        dropout_rate=self._dropout_rate,
        dtype=self.compute_dtype,
        use_state_input=self._use_state_input,
        num_task_embeddings=self._num_task_embeddings)

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    logits = inference_outputs['action_logits']  # [B, T, A*V]
    actions = jnp.asarray(labels[self.label_key], jnp.float32)
    loss = decoders.get_discrete_action_loss(
        logits, actions, self._bin_centers, self._vocab_size)
    if self._moe_experts and 'moe_aux_loss' in inference_outputs:
      loss = loss + self._moe_aux_weight * inference_outputs['moe_aux_loss']
    predicted = decoders.get_discrete_actions(
        logits, self._action_size, self._vocab_size, self._bin_centers)
    bin_width = (self._action_max - self._action_min) / self._vocab_size
    within_bin = jnp.abs(predicted - actions) <= (bin_width * 0.5 + 1e-6)
    return loss, SpecStruct(loss=loss,
                            action_accuracy=jnp.mean(
                                within_bin.astype(jnp.float32)),
                            action_mae=jnp.mean(jnp.abs(predicted - actions)))

  def model_eval_fn(self, variables, features, labels, inference_outputs,
                    mode: str) -> SpecStruct:
    loss, metrics = self.model_train_fn(variables, features, labels,
                                        inference_outputs, mode)
    return metrics

  def pack_features(self, state, context, timestep) -> dict:
    """Rolling episode window for robot-time serving.

    ``state``: observation dict with 'image' ([H, W, 3] uint8 at SOURCE
    resolution). ``context``: the previous call's return value (None on
    the first step — SequentialRegressionPolicy threads it,
    policies/policies.py:228). The newest frame enters at the end of the
    [1, T, H, W, 3] window; before T real frames exist the first frame
    repeats, matching the training-time padding convention that episode
    starts see a static camera.
    """
    frame = np.asarray(state['image'], np.uint8)[None, None]  # [1,1,H,W,3]
    if context is None:
      window = np.repeat(frame, self._episode_length, axis=1)
    else:
      prev = np.asarray(context['image'])
      window = np.concatenate([prev[:, 1:], frame], axis=1)
    packed = {'image': window}
    if self._num_task_embeddings:
      if 'task_id' not in state:
        raise ValueError(
            'Task-conditioned model (num_task_embeddings={}) requires a '
            "'task_id' in the observation.".format(
                self._num_task_embeddings))
      task_id = int(np.asarray(state['task_id']).reshape(()))
      if not 0 <= task_id < self._num_task_embeddings:
        raise ValueError('task_id {} out of range [0, {}).'.format(
            task_id, self._num_task_embeddings))
      packed['task_id'] = np.asarray([[task_id]], np.int32)
    return packed

  def create_export_outputs_fn(self, features, inference_outputs, mode: str
                               ) -> SpecStruct:
    logits = inference_outputs['action_logits']
    action = decoders.get_discrete_actions(
        logits, self._action_size, self._vocab_size, self._bin_centers)
    return SpecStruct(
        action=action,                      # [B, T, A]
        inference_output=action[:, -1, :],  # robot-time: newest step's action
        action_logits=logits)
