from tensor2robot_tpu.research.seq2act.seq2act_model import (
    RT1StyleNet,
    Seq2ActBCModel,
    Seq2ActPreprocessor,
)
