"""VRGripper env models: BC regression + domain-adaptive (DAML) variants.

Parity target: /root/reference/research/vrgripper/vrgripper_env_models.py
(DefaultVRGripperPreprocessor :46, VRGripperRegressionModel :145,
VRGripperDomainAdaptiveModel :332). The TF1 responsibilities map as:

  * distortion.preprocess_image + tf.image resize (ref :108-141) -> pure
    JAX crop (per-episode offsets shared across time) + bilinear
    ``jax.image.resize`` + mixup, all inside the jitted step.
  * slim towers under variable scopes -> Flax modules over the shared
    ``layers.vision_layers`` towers.
  * the DAML is_inner_loop/is_outer_loss params plumbing (ref :382-448) ->
    the network emits BOTH the standard and the video-only (inner) heads
    from one shared vision tower, and the model exposes
    ``inner_loop_loss_fn`` which the MAML wrapper uses for adaptation.

Episode data layout: every feature/label carries a leading fixed
``episode_length`` time dim per example — batches are [B, T, ...].
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import mdn
from tensor2robot_tpu.layers import vision_layers
from tensor2robot_tpu.meta_learning import meta_data
from tensor2robot_tpu.models.regression_model import RegressionModel
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.specs import algebra
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec


class DefaultVRGripperPreprocessor(AbstractPreprocessor):
  """uint8 src-res episode frames -> cropped/resized float32 (ref :46-141).

  Train mode random-crops (one offset per episode, shared across its time
  steps — a fixed camera doesn't jitter within an episode) and applies
  mixup when ``mixup_alpha > 0``; eval/predict center-crops.
  """

  def __init__(self,
               model_feature_specification_fn=None,
               model_label_specification_fn=None,
               src_img_res: Tuple[int, int] = (220, 300),
               crop_size: Tuple[int, int] = (200, 280),
               mixup_alpha: float = 0.0):
    super().__init__(model_feature_specification_fn,
                     model_label_specification_fn)
    self._src_img_res = tuple(src_img_res)
    self._crop_size = tuple(crop_size)
    self._mixup_alpha = float(mixup_alpha)

  def get_in_feature_specification(self, mode: str) -> SpecStruct:
    """Image stored at src resolution as uint8 (ref :71-88)."""
    spec = algebra.flatten_spec_structure(
        self._model_feature_specification(mode))
    out = SpecStruct()
    for key in spec:
      if key == 'image' or key.endswith('/image'):
        shape = list(spec[key].shape)
        shape[-3:-1] = self._src_img_res
        out[key] = TensorSpec.from_spec(spec[key], shape=tuple(shape),
                                        dtype=np.uint8)
      else:
        out[key] = spec[key]
    return out

  def get_in_label_specification(self, mode: str) -> SpecStruct:
    return algebra.flatten_spec_structure(
        self._model_label_specification(mode))

  def get_out_feature_specification(self, mode: str) -> SpecStruct:
    return algebra.flatten_spec_structure(
        self._model_feature_specification(mode))

  def get_out_label_specification(self, mode: str) -> SpecStruct:
    return algebra.flatten_spec_structure(
        self._model_label_specification(mode))

  def _crop_episode(self, images, offsets):
    """[B, T, H, W, C] cropped at per-episode (y, x) offsets."""
    ch, cw = self._crop_size

    def _one(episode, offset):
      return jax.lax.dynamic_slice(
          episode, (0, offset[0], offset[1], 0),
          (episode.shape[0], ch, cw, episode.shape[3]))

    return jax.vmap(_one)(images, offsets)

  def _preprocess_fn(self, features, labels, mode: str, rng=None):
    out_spec = self.get_out_feature_specification(mode)
    for key in features:
      if not (key == 'image' or key.endswith('/image')):
        continue
      images = jnp.asarray(features[key])
      squeeze = images.ndim == 4  # unbatched single episode
      if squeeze:
        images = images[None]
      batch = images.shape[0]
      src_h, src_w = self._src_img_res
      ch, cw = self._crop_size
      if mode == ModeKeys.TRAIN and (ch, cw) != (src_h, src_w):
        if rng is None:
          raise ValueError('TRAIN-mode preprocessing requires an rng key.')
        rng, ky, kx = jax.random.split(jnp.asarray(rng), 3)
        offsets = jnp.stack([
            jax.random.randint(ky, (batch,), 0, src_h - ch + 1),
            jax.random.randint(kx, (batch,), 0, src_w - cw + 1)], axis=-1)
        images = self._crop_episode(images, offsets)
      elif (ch, cw) != (src_h, src_w):
        y0, x0 = (src_h - ch) // 2, (src_w - cw) // 2
        images = images[:, :, y0:y0 + ch, x0:x0 + cw, :]
      images = jnp.asarray(images, jnp.float32) / 255.0
      target_hw = tuple(out_spec[key].shape[-3:-1])
      if target_hw != (ch, cw):
        images = jax.image.resize(
            images, images.shape[:2] + target_hw + images.shape[-1:],
            method='bilinear')
      features[key] = images[0] if squeeze else images

    if self._mixup_alpha > 0.0 and labels is not None \
        and mode == ModeKeys.TRAIN:
      if rng is None:
        raise ValueError('Mixup requires an rng key.')
      rng, kmix = jax.random.split(jnp.asarray(rng))
      lmbda = jax.random.beta(kmix, self._mixup_alpha, self._mixup_alpha)
      for struct in (features, labels):
        for key in struct:
          value = jnp.asarray(struct[key])
          if jnp.issubdtype(value.dtype, jnp.floating):
            struct[key] = lmbda * value + (1 - lmbda) * jnp.flip(value, 0)
    return features, labels


class VRGripperRegressionNet(nn.Module):
  """Per-frame vision tower + gripper concat + (MDN | pose) head (ref :231)."""

  action_size: int
  use_gripper_input: bool = True
  num_mixture_components: int = 1
  condition_mixture_stddev: bool = False
  output_mixture_sample: bool = False
  output_mean: Optional[Tuple[float, ...]] = None
  output_stddev: Optional[Tuple[float, ...]] = None

  @nn.compact
  def __call__(self, features, mode: str = ModeKeys.TRAIN,
               train: bool = False):
    def _per_frame(image):
      return vision_layers.ImagesToFeaturesNet(name='state_features')(
          image, train=train)

    feature_points, end_points = meta_data.multi_batch_apply(
        _per_frame, 2, jnp.asarray(features['image'], jnp.float32))
    fc_input = feature_points
    if self.use_gripper_input:
      fc_input = jnp.concatenate(
          [feature_points,
           jnp.asarray(features['gripper_pose'], jnp.float32)], -1)
    outputs = SpecStruct()
    if self.num_mixture_components > 1:
      dist_params = mdn.MDNParamsLayer(
          num_alphas=self.num_mixture_components,
          sample_size=self.action_size,
          condition_sigmas=self.condition_mixture_stddev,
          name='mdn_head')(fc_input)
      gm = mdn.get_mixture_distribution(
          dist_params.astype(jnp.float32), self.num_mixture_components,
          self.action_size,
          np.asarray(self.output_mean, np.float32)
          if self.output_mean is not None else None)
      if self.output_mixture_sample and self.has_rng('dropout'):
        # Stochastic action output (ref :260-262); deterministic mode when
        # no rng stream is available (serving without sampling).
        action = mdn.mixture_sample(gm, self.make_rng('dropout'))
      else:
        action = mdn.gaussian_mixture_approximate_mode(gm)
      outputs['dist_params'] = dist_params
    else:
      action = meta_data.multi_batch_apply(
          vision_layers.ImageFeaturesToPoseNet(
              num_outputs=self.action_size, name='pose_net'), 2, fc_input)
      if self.output_mean is not None and self.output_stddev is not None:
        action = (np.asarray(self.output_mean, np.float32) +
                  np.asarray(self.output_stddev, np.float32) * action)
    outputs['inference_output'] = action
    outputs['feature_points'] = feature_points
    outputs['softmax'] = end_points['softmax']
    return outputs


class VRGripperRegressionModel(RegressionModel):
  """Continuous BC regression for the VRGripper env (ref :145-328)."""

  label_key = 'action'

  def __init__(self,
               use_gripper_input: bool = True,
               normalize_outputs: bool = False,
               output_mean: Optional[Sequence[float]] = None,
               output_stddev: Optional[Sequence[float]] = None,
               outer_loss_multiplier: float = 1.0,
               num_mixture_components: int = 1,
               output_mixture_sample: bool = False,
               condition_mixture_stddev: bool = False,
               episode_length: int = 40,
               action_size: int = 7,
               preprocessor_cls=DefaultVRGripperPreprocessor,
               **kwargs):
    """Args mirror ref :148-199."""
    kwargs.setdefault('device_type', 'cpu')
    super().__init__(preprocessor_cls=preprocessor_cls, **kwargs)
    self._use_gripper_input = use_gripper_input
    self._normalize_outputs = normalize_outputs
    self._outer_loss_multiplier = outer_loss_multiplier
    self._num_mixture_components = num_mixture_components
    self._output_mixture_sample = output_mixture_sample
    self._condition_mixture_stddev = condition_mixture_stddev
    self._episode_length = episode_length
    self._action_size = action_size
    self._output_mean = None
    self._output_stddev = None
    if output_mean is not None and output_stddev is not None:
      if not len(output_mean) == len(output_stddev) == action_size:
        raise ValueError(
            'Output mean and stddev have lengths {:d} and {:d}.'.format(
                len(output_mean), len(output_stddev)))
      self._output_mean = tuple(float(x) for x in output_mean)
      self._output_stddev = tuple(float(x) for x in output_stddev)

  @property
  def action_size(self) -> int:
    return self._action_size

  @property
  def episode_length(self) -> int:
    return self._episode_length

  def get_feature_specification(self, mode: str) -> SpecStruct:
    """ref :205-217 — [T, 100, 100, 3] image + [T, 14] gripper pose."""
    del mode
    return SpecStruct(
        image=TensorSpec((self._episode_length, 100, 100, 3), np.float32,
                         name='image0', data_format='jpeg'),
        gripper_pose=TensorSpec((self._episode_length, 14), np.float32,
                                name='world_pose_gripper'))

  def get_label_specification(self, mode: str) -> SpecStruct:
    """ref :219-225."""
    del mode
    return SpecStruct(action=TensorSpec(
        (self._episode_length, self._action_size), np.float32,
        name='action_world'))

  def create_network(self) -> nn.Module:
    return VRGripperRegressionNet(
        action_size=self._action_size,
        use_gripper_input=self._use_gripper_input,
        num_mixture_components=self._num_mixture_components,
        condition_mixture_stddev=self._condition_mixture_stddev,
        output_mixture_sample=self._output_mixture_sample,
        output_mean=(self._output_mean if self._normalize_outputs
                     or self._num_mixture_components == 1 else None),
        output_stddev=(self._output_stddev if self._normalize_outputs
                       or self._num_mixture_components == 1 else None))

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    """MDN NLL or scaled MSE (ref loss_fn :315-328)."""
    action_labels = jnp.asarray(labels[self.label_key], jnp.float32)
    if self._num_mixture_components > 1:
      gm = mdn.get_mixture_distribution(
          inference_outputs['dist_params'].astype(jnp.float32),
          self._num_mixture_components, self._action_size,
          np.asarray(self._output_mean, np.float32)
          if self._normalize_outputs and self._output_mean is not None
          else None)
      loss = -jnp.mean(mdn.mixture_log_prob(gm, action_labels))
    else:
      predictions = inference_outputs['inference_output']
      loss = self._outer_loss_multiplier * jnp.mean(
          (predictions.astype(jnp.float32) - action_labels) ** 2)
    return loss, SpecStruct()

  def pack_features(self, state, context, timestep) -> dict:
    """One observation tiled to the episode length (serving)."""
    del context, timestep
    image = np.tile(np.asarray(state['image'])[None],
                    (self._episode_length, 1, 1, 1))
    pose = np.tile(np.asarray(state['pose'], np.float32)[None],
                   (self._episode_length, 1))
    return {'image': image[None], 'gripper_pose': pose[None]}


class VRGripperDomainAdaptiveNet(nn.Module):
  """DAML network: shared tower, standard + video-only heads, learned loss.

  The policy lives under the 'policy' scope (adapted in the inner loop);
  the learned loss under 'learned_loss' (meta-trained only) — the MAML
  wrapper's var_scope='policy' freezes it during adaptation.
  """

  action_size: int
  predict_con_gripper_pose: bool = False
  learned_loss_conv1d_layers: Optional[Tuple[int, ...]] = (10, 10, 6)
  output_mean: Optional[Tuple[float, ...]] = None
  output_stddev: Optional[Tuple[float, ...]] = None

  @nn.compact
  def __call__(self, features, mode: str = ModeKeys.TRAIN,
               train: bool = False):
    images = jnp.asarray(features['image'], jnp.float32)
    gripper_pose = jnp.asarray(features['gripper_pose'], jnp.float32)

    def _tower(image):
      return vision_layers.ImagesToFeaturesNet(name='state_features')(
          image, train=train)

    class _Policy(nn.Module):
      """Groups adapted params under one scope for var_scope filtering."""
      action_size: int
      predict_con_gripper_pose: bool

      @nn.compact
      def __call__(self, images, gripper_pose):
        feature_points, end_points = meta_data.multi_batch_apply(
            _tower, 2, images)
        # Inner (video-only) gripper pose: predicted or zeros (ref :382-388).
        if self.predict_con_gripper_pose:
          con_pose = meta_data.multi_batch_apply(
              _PredictGripperPose(name='gripper_pose_predictor'), 2,
              feature_points)
        else:
          con_pose = jnp.zeros_like(gripper_pose)
        pose_net = vision_layers.ImageFeaturesToPoseNet(
            num_outputs=self.action_size, name='pose_net')

        def _head(fp, aux):
          return pose_net(fp, aux_input=aux)

        action = meta_data.multi_batch_apply(
            _head, 2, feature_points, gripper_pose)
        action_inner = meta_data.multi_batch_apply(
            _head, 2, feature_points, con_pose)
        return action, action_inner, feature_points, end_points

    action, action_inner, feature_points, end_points = _Policy(
        self.action_size, self.predict_con_gripper_pose, name='policy')(
            images, gripper_pose)
    if self.output_mean is not None and self.output_stddev is not None:
      mean = np.asarray(self.output_mean, np.float32)
      stddev = np.asarray(self.output_stddev, np.float32)
      action = mean + stddev * action
      action_inner = mean + stddev * action_inner

    outputs = SpecStruct(
        inference_output=action,
        inference_output_inner=action_inner,
        feature_points=feature_points)
    outputs['softmax'] = end_points['softmax']
    outputs['learned_loss_value'] = _LearnedLoss(
        action_size=self.action_size,
        conv1d_layers=self.learned_loss_conv1d_layers,
        name='learned_loss')(feature_points, action_inner)
    return outputs


class _PredictGripperPose(nn.Module):
  """Condition gripper pose from feature points (ref :356-362)."""

  @nn.compact
  def __call__(self, feature_points):
    out = nn.Dense(40, use_bias=False)(feature_points)
    out = nn.LayerNorm()(out)
    out = nn.relu(out)
    return nn.Dense(14)(out)


class _LearnedLoss(nn.Module):
  """Temporal conv learned loss (ref model_train_fn :426-448)."""

  action_size: int
  conv1d_layers: Optional[Tuple[int, ...]] = (10, 10, 6)

  @nn.compact
  def __call__(self, feature_points, inference_output):
    predicted_action = meta_data.multi_batch_apply(
        vision_layers.ImageFeaturesToPoseNet(
            num_outputs=self.action_size, name='ll_pose'), 2,
        feature_points)
    if self.conv1d_layers is None:
      return jnp.mean(
          (predicted_action - jax.lax.stop_gradient(inference_output)) ** 2)
    net = jnp.concatenate(
        [predicted_action, feature_points, inference_output], -1)
    for i, num_filters in enumerate(self.conv1d_layers[:-1]):
      net = nn.Conv(num_filters, (10,), padding='VALID', use_bias=False,
                    name='ll_conv{}'.format(i))(net)
      net = nn.relu(net)
      net = nn.LayerNorm()(net)
    net = nn.Conv(self.conv1d_layers[-1], (1,), name='ll_conv_out')(net)
    return jnp.mean(jnp.sum(jnp.square(net), axis=(1, 2)))


class VRGripperDomainAdaptiveModel(VRGripperRegressionModel):
  """Learned-loss domain-adaptive imitation (ref :332-448).

  Wrap with ``MAMLRegressionModel(base_model=...,
  inner_loop=MAMLInnerLoopGradientDescent(var_scope='policy'))`` so only
  the policy adapts and the learned loss is meta-trained by the outer loop.
  """

  def __init__(self,
               predict_con_gripper_pose: bool = False,
               learned_loss_conv1d_layers: Tuple[int, ...] = (10, 10, 6),
               **kwargs):
    super().__init__(**kwargs)
    self._predict_con_gripper_pose = predict_con_gripper_pose
    self._learned_loss_conv1d_layers = (
        tuple(learned_loss_conv1d_layers)
        if learned_loss_conv1d_layers is not None else None)

  def create_network(self) -> nn.Module:
    return VRGripperDomainAdaptiveNet(
        action_size=self._action_size,
        predict_con_gripper_pose=self._predict_con_gripper_pose,
        learned_loss_conv1d_layers=self._learned_loss_conv1d_layers,
        output_mean=self._output_mean,
        output_stddev=self._output_stddev)

  def inner_loop_loss_fn(self, variables, features, labels,
                         inference_outputs, mode: str):
    """The learned loss drives inner-loop adaptation (ref :426-448)."""
    del variables, features, labels
    return inference_outputs['learned_loss_value'], SpecStruct()

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    """Outer loss: standard behavior cloning (ref :423-425)."""
    action_labels = jnp.asarray(labels[self.label_key], jnp.float32)
    predictions = inference_outputs['inference_output']
    loss = self._outer_loss_multiplier * jnp.mean(
        (predictions.astype(jnp.float32) - action_labels) ** 2)
    return loss, SpecStruct()
