"""VRGripper meta models: MAML wrapper, TEC, and SNAIL/RL^2 sequential BC.

Parity target: /root/reference/research/vrgripper/vrgripper_env_meta_models.py
(pack_vrgripper_meta_features :46, VRGripperEnvRegressionModelMAML :123,
VRGripperEnvTecModel :143, VRGripperEnvSequentialModel :421). TF1
responsibilities map as:

  * tf.map_fn / multi_batch_apply scope reuse -> shared Flax submodules
    applied over merged [task, episode] batch dims.
  * mdn/MAF/MSE decoder objects caching tensors for .loss() -> the decoder
    modules of ``research.vrgripper.decoders`` computing action and loss in
    one call inside the jitted step.
  * the internal metatidy SNAIL (ref :435, not in OSS) -> an explicit
    per-frame vision tower + TCBlock/AttentionBlock stack from
    ``layers.snail`` over the concatenated condition+inference sequence.

Meta feature layout (flat keys, fixed sample counts):
  condition/features/image        [B, n_cond, T, 100, 100, 3]
  condition/features/gripper_pose [B, n_cond, T, 14]
  condition/labels/action         [B, n_cond, T, A]
  inference/features/*            [B, n_inf, T, ...]
  labels: action                  [B, n_inf, T, A]
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple, Type

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import snail
from tensor2robot_tpu.layers import tec
from tensor2robot_tpu.layers import vision_layers
from tensor2robot_tpu.meta_learning import meta_data
from tensor2robot_tpu.meta_learning import preprocessors as meta_preprocessors
from tensor2robot_tpu.meta_learning.maml_model import MAMLRegressionModel
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.research.vrgripper import decoders
from tensor2robot_tpu.research.vrgripper import vrgripper_env_models
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec


def pack_vrgripper_meta_features(state, prev_episode_data, timestep,
                                 fixed_length: int,
                                 num_condition_samples_per_task: int
                                 ) -> Dict[str, np.ndarray]:
  """Current state + conditioning episodes -> meta feed dict (ref :46-119).

  ``state``: dict/namedtuple with 'image' (uint8 [H, W, 3]) and 'pose'.
  ``prev_episode_data``: list of episodes; each a list of
  (obs, action, rew, new_obs, done, debug) tuples whose obs carry
  image/pose.
  """
  del timestep
  if len(prev_episode_data) < 1:
    raise ValueError(
        'prev_episode_data should at least contain one (demo) episode.')

  def _get(obj, key):
    return obj[key] if isinstance(obj, dict) else getattr(obj, key)

  features = {}
  image = np.asarray(_get(state, 'image'))
  pose = np.asarray(_get(state, 'pose'), np.float32)
  features['inference/features/image'] = np.tile(
      image[None], (fixed_length,) + (1,) * image.ndim).astype(np.uint8)
  features['inference/features/gripper_pose'] = np.tile(
      pose[None], (fixed_length,) + (1,) * pose.ndim)

  cond_images, cond_poses, cond_actions = [], [], []
  from tensor2robot_tpu.research.vrgripper.episode_to_transitions import (
      make_fixed_length,
  )
  for i in range(num_condition_samples_per_task):
    episode = make_fixed_length(
        prev_episode_data[i % len(prev_episode_data)], fixed_length)
    cond_images.append(np.stack(
        [np.asarray(_get(t[0], 'image')) for t in episode]))
    cond_poses.append(np.stack(
        [np.asarray(_get(t[0], 'pose'), np.float32) for t in episode]))
    cond_actions.append(np.stack(
        [np.asarray(t[1], np.float32) for t in episode]))
  features['condition/features/image'] = np.stack(cond_images).astype(
      np.uint8)
  features['condition/features/gripper_pose'] = np.stack(cond_poses)
  features['condition/labels/action'] = np.stack(cond_actions)
  # Meta (task) batch dim; inference features also gain the episodes dim.
  for key in list(features):
    if key.startswith('inference/'):
      features[key] = features[key][None]
    features[key] = features[key][None]
  return features


class VRGripperEnvRegressionModelMAML(MAMLRegressionModel):
  """MAML over the VRGripper regression model (ref :123-139)."""

  def pack_features(self, state, prev_episode_data, timestep):
    return pack_vrgripper_meta_features(
        state, prev_episode_data, timestep,
        self._base_model.episode_length, 1)


class _FixedCountMetaModel(AbstractT2RModel):
  """Shared plumbing for standalone meta models (TEC / SNAIL / WTL).

  Declares the fixed-count meta specs from per-episode specs and routes
  labels into the network so decoder losses are computed in-graph.
  """

  def __init__(self,
               episode_length: int = 40,
               num_condition_samples_per_task: int = 1,
               num_inference_samples_per_task: int = 1,
               **kwargs):
    kwargs.setdefault('device_type', 'cpu')
    super().__init__(**kwargs)
    self._episode_length = episode_length
    self._num_condition = num_condition_samples_per_task
    self._num_inference = num_inference_samples_per_task

  def _episode_feature_specification(self, mode: str) -> SpecStruct:
    raise NotImplementedError

  def _episode_label_specification(self, mode: str) -> SpecStruct:
    raise NotImplementedError

  def _base_preprocessor_cls(self):
    return vrgripper_env_models.DefaultVRGripperPreprocessor

  @property
  def preprocessor(self):
    if self._preprocessor is None:
      base = self._base_preprocessor_cls()(
          model_feature_specification_fn=self._episode_feature_specification,
          model_label_specification_fn=self._episode_label_specification)
      self._preprocessor = meta_preprocessors.FixedLenMetaExamplePreprocessor(
          base_preprocessor=base,
          num_condition_samples_per_task=self._num_condition,
          num_inference_samples_per_task=self._num_inference)
    return self._preprocessor

  def get_feature_specification(self, mode: str) -> SpecStruct:
    return meta_preprocessors.create_maml_feature_spec(
        self._episode_feature_specification(mode),
        self._episode_label_specification(mode),
        self._num_condition, self._num_inference)

  def get_label_specification(self, mode: str) -> SpecStruct:
    return meta_preprocessors.create_maml_label_spec(
        self._episode_label_specification(mode), self._num_inference)

  def inference_network_fn(self, variables, features, labels=None,
                           mode: str = ModeKeys.TRAIN, rng=None):
    """Like the base default, but labels reach the network (decoder loss)."""
    import flax

    network = self.create_network()
    train = mode == ModeKeys.TRAIN
    rngs = {'dropout': rng} if rng is not None else None
    labels_dict = dict(labels) if labels is not None and len(labels) else None
    mutable = [k for k in variables if k != 'params'] if train else False
    if mutable:
      outputs, new_state = network.apply(
          variables, features, labels_dict, mode=mode, train=train,
          rngs=rngs, mutable=mutable)
      return outputs, flax.core.unfreeze(new_state)
    outputs = network.apply(variables, features, labels_dict, mode=mode,
                            train=train, rngs=rngs)
    return outputs, None


class _TecNet(nn.Module):
  """TEC network (ref :239-317): condition embedding -> policy."""

  action_size: int
  num_waypoints: int
  episode_length: int
  fc_embed_size: int
  ignore_embedding: bool
  use_film: bool
  predict_end_weight: float
  decoder_cls: Type[nn.Module]
  decoder_kwargs: Optional[Dict[str, Any]] = None

  def _embed_episode(self, embedder, reducer, images, train: bool):
    """[B, n, T, H, W, C] -> l2-normalized [B, n, embed] (ref :239-249).

    ``embedder``/``reducer`` are shared module INSTANCES (the reference's
    AUTO_REUSE variable scopes): condition and inference episodes embed
    through the same weights.
    """
    image_embedding = meta_data.multi_batch_apply(
        lambda im: embedder(im, train=train), 3, images)
    embedding = meta_data.multi_batch_apply(reducer, 2, image_embedding)
    return embedding / jnp.maximum(
        jnp.linalg.norm(embedding, axis=-1, keepdims=True), 1e-12)

  @nn.compact
  def __call__(self, features, labels=None, mode: str = ModeKeys.TRAIN,
               train: bool = False):
    condition_images = jnp.asarray(
        features['condition/features/image'], jnp.float32)
    inference_images = jnp.asarray(
        features['inference/features/image'], jnp.float32)
    gripper_pose = jnp.asarray(
        features['inference/features/gripper_pose'], jnp.float32)

    embedder = tec.EmbedConditionImages(name='image_embedding')
    reducer = tec.ReduceTemporalEmbeddings(self.fc_embed_size,
                                           name='fc_reduce')
    condition_embedding = self._embed_episode(embedder, reducer,
                                              condition_images, train)

    film_output_params = None
    if self.use_film:
      film_output_params = meta_data.multi_batch_apply(
          vision_layers.FilmParams(name='film_params'), 2,
          condition_embedding)
      film_output_params = jnp.broadcast_to(
          film_output_params[:, :, None, :],
          film_output_params.shape[:2] + (self.episode_length,) +
          film_output_params.shape[-1:])

    def _tower(image, film):
      return vision_layers.ImagesToFeaturesNet(name='state_features')(
          image, film_output_params=film, train=train)

    if film_output_params is None:
      state_features, _ = meta_data.multi_batch_apply(
          lambda im: _tower(im, None), 3, inference_images)
    else:
      state_features, _ = meta_data.multi_batch_apply(
          _tower, 3, inference_images, film_output_params)

    fc_embedding = jnp.broadcast_to(
        condition_embedding[..., :self.fc_embed_size][:, :, None, :],
        state_features.shape[:3] + (self.fc_embed_size,))
    if self.ignore_embedding:
      fc_inputs = jnp.concatenate([state_features, gripper_pose], -1)
    else:
      fc_inputs = jnp.concatenate(
          [state_features, gripper_pose, fc_embedding], -1)

    aux_output_dim = 1 if self.predict_end_weight > 0 else 0
    pose_net = vision_layers.ImageFeaturesToPoseNet(
        num_outputs=None, aux_output_dim=aux_output_dim, name='a_func')
    if aux_output_dim:
      action_params, end_token = meta_data.multi_batch_apply(
          pose_net, 3, fc_inputs)
    else:
      action_params = meta_data.multi_batch_apply(pose_net, 3, fc_inputs)
      end_token = None

    decoder = self.decoder_cls(
        output_size=self.num_waypoints * self.action_size,
        name='action_decoder', **(self.decoder_kwargs or {}))
    decoded = decoder(
        action_params,
        labels_action=None if labels is None else labels['action'])

    outputs = SpecStruct(
        inference_output=decoded['action'],
        condition_embedding=condition_embedding)
    if 'loss' in decoded:
      outputs['bc_loss'] = decoded['loss']
    if end_token is not None:
      outputs['end_token_logits'] = end_token
      outputs['end_token'] = jax.nn.sigmoid(end_token)
      outputs['inference_output'] = jnp.concatenate(
          [outputs['inference_output'], outputs['end_token']], -1)
    if mode != ModeKeys.PREDICT:
      outputs['inference_embedding'] = self._embed_episode(
          embedder, reducer, inference_images, train)
    return outputs


class VRGripperEnvTecModel(_FixedCountMetaModel):
  """Task-Embedded Control network (ref :143-417, arXiv:1810.03237)."""

  def __init__(self,
               action_size: int = 7,
               gripper_pose_size: int = 14,
               num_waypoints: int = 1,
               embed_loss_weight: float = 0.0,
               fc_embed_size: int = 32,
               ignore_embedding: bool = False,
               action_decoder_cls: Type[nn.Module] = decoders.MDNActionDecoder,
               action_decoder_kwargs: Optional[dict] = None,
               predict_end_weight: float = 0.0,
               use_film: bool = False,
               **kwargs):
    super().__init__(**kwargs)
    self._action_size = action_size
    self._gripper_pose_size = gripper_pose_size
    self._num_waypoints = num_waypoints
    self._embed_loss_weight = embed_loss_weight
    self._fc_embed_size = fc_embed_size
    self._ignore_embedding = ignore_embedding
    self._action_decoder_cls = action_decoder_cls
    self._action_decoder_kwargs = dict(action_decoder_kwargs or {})
    self._predict_end_weight = predict_end_weight
    self._use_film = use_film

  def _episode_feature_specification(self, mode: str) -> SpecStruct:
    """ref :190-203."""
    del mode
    return SpecStruct(
        image=TensorSpec((self._episode_length, 100, 100, 3), np.float32,
                         name='image0', data_format='jpeg'),
        gripper_pose=TensorSpec(
            (self._episode_length, self._gripper_pose_size), np.float32,
            name='world_pose_gripper'))

  def _episode_label_specification(self, mode: str) -> SpecStruct:
    """ref :205-214."""
    del mode
    return SpecStruct(action=TensorSpec(
        (self._episode_length, self._num_waypoints * self._action_size),
        np.float32, name='action_world'))

  def create_network(self) -> nn.Module:
    return _TecNet(
        action_size=self._action_size,
        num_waypoints=self._num_waypoints,
        episode_length=self._episode_length,
        fc_embed_size=self._fc_embed_size,
        ignore_embedding=self._ignore_embedding,
        use_film=self._use_film,
        predict_end_weight=self._predict_end_weight,
        decoder_cls=self._action_decoder_cls,
        decoder_kwargs=self._action_decoder_kwargs or None)

  def _end_loss(self, inference_outputs) -> jnp.ndarray:
    """Last two steps labeled as end states (ref :319-333)."""
    if self._predict_end_weight <= 0:
      return jnp.zeros((), jnp.float32)
    logits = inference_outputs['end_token_logits'].astype(jnp.float32)
    end_labels = jnp.concatenate(
        [jnp.zeros_like(logits[:, :, :-2, :]),
         jnp.ones_like(logits[:, :, -2:, :])], 2)
    import optax
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, end_labels))

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    """bc + weighted contrastive embedding + end losses (ref :335-354)."""
    bc_loss = inference_outputs['bc_loss']
    embed_loss = tec.compute_embedding_contrastive_loss(
        inference_outputs['inference_embedding'],
        inference_outputs['condition_embedding'])
    end_loss = self._end_loss(inference_outputs)
    train_outputs = SpecStruct(bc_loss=bc_loss, embed_loss=embed_loss,
                               end_loss=end_loss)
    return (bc_loss + self._embed_loss_weight * embed_loss +
            self._predict_end_weight * end_loss), train_outputs

  def model_eval_fn(self, variables, features, labels, inference_outputs,
                    mode: str) -> SpecStruct:
    """Streaming means of the train losses (ref :356-371)."""
    loss, train_outputs = self.model_train_fn(
        variables, features, labels, inference_outputs, mode)
    metrics = SpecStruct(loss=loss)
    for key in train_outputs:
      metrics[key] = train_outputs[key]
    return metrics

  def pack_features(self, state, prev_episode_data, timestep):
    """ref :397-417."""
    return pack_vrgripper_meta_features(
        state, prev_episode_data, timestep, self._episode_length,
        self._num_condition)


class _SnailSequenceNet(nn.Module):
  """Per-frame vision tower + SNAIL temporal stack (ref metatidy SNAIL).

  Consumes the full condition+inference frame sequence causally and emits
  one action parameterization per time step.
  """

  output_size: int
  sequence_length: int
  filters: int = 32
  key_size: int = 16
  value_size: int = 16

  @nn.compact
  def __call__(self, images, aux_input, train: bool = False):
    state_features, _ = meta_data.multi_batch_apply(
        lambda im: vision_layers.ImagesToFeaturesNet(
            name='state_features')(im, train=train), 2, images)
    net = jnp.concatenate([state_features, aux_input], -1)
    net = snail.TCBlock(self.sequence_length, self.filters, name='tc1')(net)
    net, _ = snail.AttentionBlock(self.key_size, self.value_size,
                                  name='attn1')(net)
    net = snail.TCBlock(self.sequence_length, self.filters, name='tc2')(net)
    net, end_points = snail.AttentionBlock(self.key_size, self.value_size,
                                           name='attn2')(net)
    poses = nn.Dense(self.output_size, name='poses')(net)
    return poses, {'attn_probs/0': end_points['attn_prob']}


class VRGripperEnvSequentialModel(VRGripperEnvTecModel):
  """RL^2 / SNAIL sequential meta-learner (ref :421-533).

  Conditions causally on the (optionally action-blind) demo sequence
  followed by the inference sequence; only the inference tail is decoded.
  """

  def __init__(self,
               condition_gripper_pose: bool = False,
               num_mixture_components: int = 1,
               greedy_action: bool = False,
               **kwargs):
    super().__init__(**kwargs)
    self._condition_gripper_pose = condition_gripper_pose
    self._num_mixture_components = num_mixture_components
    self._greedy_action = greedy_action

  def create_network(self) -> nn.Module:
    return _SequentialNet(
        action_size=self._action_size,
        episode_length=self._episode_length,
        num_mixture_components=self._num_mixture_components,
        condition_gripper_pose=self._condition_gripper_pose)

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    """NLL or MSE over the inference tail (ref :514-533)."""
    bc_loss = inference_outputs['bc_loss']
    return bc_loss, SpecStruct(bc_loss=bc_loss)

  def model_eval_fn(self, variables, features, labels, inference_outputs,
                    mode: str) -> SpecStruct:
    loss, train_outputs = self.model_train_fn(
        variables, features, labels, inference_outputs, mode)
    metrics = SpecStruct(loss=loss)
    for key in train_outputs:
      metrics[key] = train_outputs[key]
    return metrics


class _SequentialNet(nn.Module):
  """Wires _SnailSequenceNet into the meta feature layout (ref :458-512)."""

  action_size: int
  episode_length: int
  num_mixture_components: int = 1
  condition_gripper_pose: bool = False

  @nn.compact
  def __call__(self, features, labels=None, mode: str = ModeKeys.TRAIN,
               train: bool = False):
    condition_images = jnp.asarray(
        features['condition/features/image'], jnp.float32)
    inference_images = jnp.asarray(
        features['inference/features/image'], jnp.float32)
    cond_pose = jnp.asarray(
        features['condition/features/gripper_pose'], jnp.float32)
    inf_pose = jnp.asarray(
        features['inference/features/gripper_pose'], jnp.float32)
    if not self.condition_gripper_pose:
      # Imitation-from-video: no demo actions/poses (ref :471-473).
      cond_pose = jnp.zeros_like(cond_pose)
    condition_sequence_length = condition_images.shape[2]

    # Episode 0 of condition + episode 0 of inference, across time (ref
    # :475-481: "assuming only 1 condition, 1 inference batch for now").
    images = jnp.concatenate(
        [condition_images[:, 0], inference_images[:, 0]], axis=1)
    aux_input = jnp.concatenate([cond_pose[:, 0], inf_pose[:, 0]], axis=1)

    if self.num_mixture_components > 1:
      num_mus = self.action_size * self.num_mixture_components
      num_outputs = self.num_mixture_components + 2 * num_mus
    else:
      num_outputs = self.action_size
    poses, end_points = _SnailSequenceNet(
        output_size=num_outputs,
        sequence_length=images.shape[1],
        name='snail')(images, aux_input, train=train)

    outputs = SpecStruct()
    tail = poses[:, condition_sequence_length:]
    if self.num_mixture_components > 1:
      from tensor2robot_tpu.layers import mdn
      gm = mdn.get_mixture_distribution(
          tail.astype(jnp.float32), self.num_mixture_components,
          self.action_size)
      inference_poses = mdn.gaussian_mixture_approximate_mode(gm)
      if labels is not None:
        action_labels = jnp.asarray(labels['action'],
                                    jnp.float32)[:, 0]  # episode 0
        outputs['bc_loss'] = -jnp.mean(mdn.mixture_log_prob(
            gm, action_labels))
    else:
      inference_poses = tail
      if labels is not None:
        action_labels = jnp.asarray(labels['action'], jnp.float32)[:, 0]
        outputs['bc_loss'] = jnp.mean(
            (tail.astype(jnp.float32) - action_labels) ** 2)
    outputs['inference_output'] = inference_poses[:, None]
    for key, value in end_points.items():
      outputs[key] = value
    return outputs
