"""Watch-Try-Learn trial/retrial models (arXiv:1906.03352).

Parity target: /root/reference/research/vrgripper/vrgripper_env_wtl_models.py
(pack_wtl_meta_features :46, VRGripperEnvSimpleTrialModel :140 — low-dim
state, VRGripperEnvVisionTrialModel :359 — vision). The trial model
conditions on the demo episode; the retrial variant additionally embeds the
first trial episode together with its success signal, which is carried in
``condition/labels/success``.

Meta feature layout (fixed sample counts; retrial uses 2 condition
episodes: [demo, trial]):
  condition/features/full_state_pose | image,gripper_pose
  condition/labels/action, condition/labels/success
  inference/features/*, labels: action [+ success]
"""

from __future__ import annotations

from typing import Dict, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import mdn
from tensor2robot_tpu.layers import tec
from tensor2robot_tpu.layers import vision_layers
from tensor2robot_tpu.meta_learning import meta_data
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.research.vrgripper.episode_to_transitions import (
    make_fixed_length,
)
from tensor2robot_tpu.research.vrgripper.vrgripper_env_meta_models import (
    _FixedCountMetaModel,
)
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec


def pack_wtl_meta_features(state, prev_episode_data, timestep,
                           fixed_length: int,
                           num_condition_samples_per_task: int,
                           vision: bool = False,
                           deterministic_condition: bool = True
                           ) -> Dict[str, np.ndarray]:
  """State + conditioning episodes -> WTL meta feed dict (ref :46-136)."""
  del timestep
  if len(prev_episode_data) < 1:
    raise ValueError(
        'prev_episode_data should at least contain one (demo) episode.')

  def _get(obj, key):
    return obj[key] if isinstance(obj, dict) else getattr(obj, key)

  features = {}
  if vision:
    image = np.asarray(_get(state, 'image'))
    pose = np.asarray(_get(state, 'pose'), np.float32)
    features['inference/features/image'] = np.tile(
        image[None], (fixed_length,) + (1,) * image.ndim).astype(np.uint8)
    features['inference/features/gripper_pose'] = np.tile(
        pose[None], (fixed_length,) + (1,) * pose.ndim)
  else:
    full_state = np.asarray(_get(state, 'full_state_pose'), np.float32)
    features['inference/features/full_state_pose'] = np.tile(
        full_state[None], (fixed_length,) + (1,) * full_state.ndim)

  packed = {k: [] for k in ('image', 'gripper_pose', 'full_state_pose',
                            'action', 'success')}
  for i in range(num_condition_samples_per_task):
    episode = make_fixed_length(
        prev_episode_data[i % len(prev_episode_data)], fixed_length,
        randomized=not deterministic_condition)
    if vision:
      packed['image'].append(np.stack(
          [np.asarray(_get(t[0], 'image')) for t in episode]))
      packed['gripper_pose'].append(np.stack(
          [np.asarray(_get(t[0], 'pose'), np.float32) for t in episode]))
    else:
      packed['full_state_pose'].append(np.stack(
          [np.asarray(_get(t[0], 'full_state_pose'), np.float32)
           for t in episode]))
    packed['action'].append(np.stack(
        [np.asarray(t[1], np.float32) for t in episode]))
    cumulative_return = np.sum([t[2] for t in episode])
    packed['success'].append(
        float(cumulative_return > 0) * np.ones((fixed_length, 1),
                                               np.float32))
  if vision:
    features['condition/features/image'] = np.stack(
        packed['image']).astype(np.uint8)
    features['condition/features/gripper_pose'] = np.stack(
        packed['gripper_pose'])
  else:
    features['condition/features/full_state_pose'] = np.stack(
        packed['full_state_pose'])
  features['condition/labels/action'] = np.stack(packed['action'])
  features['condition/labels/success'] = np.stack(packed['success'])
  for key in list(features):
    if key.startswith('inference/'):
      features[key] = features[key][None]
    features[key] = features[key][None]
  return features


class _SimpleTrialNet(nn.Module):
  """Low-dim WTL policy (ref VRGripperEnvSimpleTrialModel :216-288)."""

  action_size: int
  episode_length: int
  fc_embed_size: int
  ignore_embedding: bool
  num_mixture_components: int
  retrial: bool
  embed_type: str

  @nn.compact
  def __call__(self, features, labels=None, mode: str = ModeKeys.TRAIN,
               train: bool = False):
    inf_pose = jnp.asarray(
        features['inference/features/full_state_pose'], jnp.float32)
    con_pose = jnp.asarray(
        features['condition/features/full_state_pose'], jnp.float32)
    # Success labels [0, 1] -> [-1, 1] (ref :227).
    con_success = 2.0 * jnp.asarray(
        features['condition/labels/success'], jnp.float32) - 1.0
    if self.retrial and con_pose.shape[1] != 2:
      raise ValueError('Unexpected shape {}.'.format(con_pose.shape))

    episode_length = inf_pose.shape[2]
    if self.embed_type == 'temporal':
      fc_embedding = meta_data.multi_batch_apply(
          tec.ReduceTemporalEmbeddings(self.fc_embed_size,
                                       name='demo_embedding'), 2,
          con_pose[:, 0:1, :, :])[:, :, None, :]
    elif self.embed_type == 'mean':
      fc_embedding = con_pose[:, 0:1, -1:, :]
    else:
      raise ValueError('Invalid embed_type: {}.'.format(self.embed_type))
    fc_embedding = jnp.broadcast_to(
        fc_embedding,
        fc_embedding.shape[:2] + (episode_length,) + fc_embedding.shape[-1:])

    if self.retrial:
      # Embed the trial episode with its success signal (ref :240-255).
      con_input = jnp.concatenate(
          [con_pose[:, 1:2, :, :], con_success[:, 1:2, :, :], fc_embedding],
          -1)
      if self.embed_type == 'mean':
        trial_embedding = meta_data.multi_batch_apply(
            tec.EmbedFullstate(self.fc_embed_size, name='trial_embedding'),
            3, con_input)
        trial_embedding = jnp.mean(trial_embedding, -2)
      else:
        trial_embedding = meta_data.multi_batch_apply(
            tec.ReduceTemporalEmbeddings(self.fc_embed_size,
                                         name='trial_embedding'), 2,
            con_input)
      trial_embedding = jnp.broadcast_to(
          trial_embedding[:, :, None, :],
          trial_embedding.shape[:2] + (episode_length,) +
          trial_embedding.shape[-1:])
      fc_embedding = jnp.concatenate([fc_embedding, trial_embedding], -1)

    if self.ignore_embedding:
      fc_inputs = inf_pose
    else:
      parts = [inf_pose, fc_embedding]
      if self.retrial:
        parts.append(con_success[:, 1:2, :, :])
      fc_inputs = jnp.concatenate(parts, -1)

    outputs = SpecStruct()
    if self.num_mixture_components > 1:
      hidden = meta_data.multi_batch_apply(
          vision_layers.ImageFeaturesToPoseNet(
              num_outputs=None, name='a_func'), 3, fc_inputs)
      dist_params = mdn.MDNParamsLayer(
          num_alphas=self.num_mixture_components,
          sample_size=self.action_size, condition_sigmas=False,
          name='mdn_head')(hidden)
      outputs['dist_params'] = dist_params
      gm = mdn.get_mixture_distribution(
          dist_params.astype(jnp.float32), self.num_mixture_components,
          self.action_size)
      action = mdn.gaussian_mixture_approximate_mode(gm)
      if labels is not None:
        outputs['bc_loss'] = -jnp.mean(mdn.mixture_log_prob(
            gm, jnp.asarray(labels['action'], jnp.float32)))
    else:
      action = meta_data.multi_batch_apply(
          vision_layers.ImageFeaturesToPoseNet(
              num_outputs=self.action_size, name='a_func'), 3, fc_inputs)
      if labels is not None:
        outputs['bc_loss'] = jnp.mean(
            (action.astype(jnp.float32) -
             jnp.asarray(labels['action'], jnp.float32)) ** 2)
    outputs['inference_output'] = action
    return outputs


class VRGripperEnvSimpleTrialModel(_FixedCountMetaModel):
  """Low-dim-state WTL trial/retrial model (ref :140-355)."""

  def __init__(self,
               action_size: int = 7,
               fc_embed_size: int = 32,
               ignore_embedding: bool = False,
               num_mixture_components: int = 1,
               retrial: bool = False,
               embed_type: str = 'temporal',
               obs_size: int = 32,
               **kwargs):
    if retrial:
      kwargs.setdefault('num_condition_samples_per_task', 2)
    super().__init__(**kwargs)
    self._action_size = action_size
    self._fc_embed_size = fc_embed_size
    self._ignore_embedding = ignore_embedding
    self._num_mixture_components = num_mixture_components
    self._retrial = retrial
    self._embed_type = embed_type
    self._obs_size = obs_size

  def _episode_feature_specification(self, mode: str) -> SpecStruct:
    """ref :168-178."""
    del mode
    return SpecStruct(full_state_pose=TensorSpec(
        (self._episode_length, self._obs_size), np.float32,
        name='full_state_pose'))

  def _episode_label_specification(self, mode: str) -> SpecStruct:
    """ref :180-190."""
    del mode
    return SpecStruct(
        action=TensorSpec((self._episode_length, self._action_size),
                          np.float32, name='action_world'),
        success=TensorSpec((self._episode_length, 1), np.float32,
                           name='success'))

  def create_network(self) -> nn.Module:
    return _SimpleTrialNet(
        action_size=self._action_size,
        episode_length=self._episode_length,
        fc_embed_size=self._fc_embed_size,
        ignore_embedding=self._ignore_embedding,
        num_mixture_components=self._num_mixture_components,
        retrial=self._retrial,
        embed_type=self._embed_type)

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    """ref :290-312."""
    bc_loss = inference_outputs['bc_loss']
    return bc_loss, SpecStruct(bc_loss=bc_loss)

  def model_eval_fn(self, variables, features, labels, inference_outputs,
                    mode: str) -> SpecStruct:
    loss, train_outputs = self.model_train_fn(
        variables, features, labels, inference_outputs, mode)
    metrics = SpecStruct(loss=loss)
    for key in train_outputs:
      metrics['mean_' + key] = train_outputs[key]
    return metrics

  def pack_features(self, state, prev_episode_data, timestep):
    """ref :335-355."""
    return pack_wtl_meta_features(
        state, prev_episode_data, timestep, self._episode_length,
        self._num_condition)


class _VisionTrialNet(nn.Module):
  """Vision WTL policy (ref VRGripperEnvVisionTrialModel :435-505)."""

  action_size: int
  episode_length: int
  fc_embed_size: int
  ignore_embedding: bool
  num_mixture_components: int
  num_condition_samples_per_task: int

  def _embed_episode(self, episode_images, gripper_pose, success, train):
    """Demo (+trial w/ success) embedding (ref :435-462)."""
    # One shared image embedder (the reference's AUTO_REUSE
    # 'image_embedding' scope serves both demo and trial frames).
    embedder = tec.EmbedConditionImages(name='image_embedding')
    demo_fp = meta_data.multi_batch_apply(
        lambda im: embedder(im, train=train), 3, episode_images[:, 0:1])
    demo_inputs = jnp.concatenate([demo_fp, gripper_pose[:, 0:1]], -1)
    embedding = meta_data.multi_batch_apply(
        tec.ReduceTemporalEmbeddings(self.fc_embed_size,
                                     name='fc_demo_reduce'), 2, demo_inputs)
    if self.num_condition_samples_per_task > 1:
      con_success = 2.0 * success - 1.0
      trial_fp = meta_data.multi_batch_apply(
          lambda im: embedder(im, train=train), 3, episode_images[:, 1:2])
      episode_length = episode_images.shape[2]
      trial_inputs = jnp.concatenate(
          [trial_fp, gripper_pose[:, 1:2], con_success[:, 1:2],
           jnp.broadcast_to(
               embedding[:, :, None, :],
               embedding.shape[:2] + (episode_length,) +
               embedding.shape[-1:])], -1)
      trial_embedding = meta_data.multi_batch_apply(
          tec.ReduceTemporalEmbeddings(self.fc_embed_size,
                                       name='fc_trial_reduce'), 2,
          trial_inputs)
      embedding = jnp.concatenate([embedding, trial_embedding], axis=-1)
    return embedding

  @nn.compact
  def __call__(self, features, labels=None, mode: str = ModeKeys.TRAIN,
               train: bool = False):
    condition_images = jnp.asarray(
        features['condition/features/image'], jnp.float32)
    con_gripper = jnp.asarray(
        features['condition/features/gripper_pose'], jnp.float32)
    con_success = jnp.asarray(
        features['condition/labels/success'], jnp.float32)
    inference_images = jnp.asarray(
        features['inference/features/image'], jnp.float32)
    gripper_pose = jnp.asarray(
        features['inference/features/gripper_pose'], jnp.float32)

    condition_embedding = self._embed_episode(
        condition_images, con_gripper, con_success, train)
    fc_embedding = jnp.broadcast_to(
        condition_embedding[:, :, None, :],
        condition_embedding.shape[:2] + (self.episode_length,) +
        condition_embedding.shape[-1:])
    state_features, _ = meta_data.multi_batch_apply(
        lambda im: vision_layers.ImagesToFeaturesNet(
            name='state_features')(im, train=train), 3, inference_images)
    if self.ignore_embedding:
      fc_inputs = jnp.concatenate([state_features, gripper_pose], -1)
    else:
      fc_inputs = jnp.concatenate(
          [state_features, gripper_pose, fc_embedding], -1)

    outputs = SpecStruct()
    if self.num_mixture_components > 1:
      dist_params = mdn.MDNParamsLayer(
          num_alphas=self.num_mixture_components,
          sample_size=self.action_size, condition_sigmas=False,
          name='mdn_head')(fc_inputs)
      outputs['dist_params'] = dist_params
      gm = mdn.get_mixture_distribution(
          dist_params.astype(jnp.float32), self.num_mixture_components,
          self.action_size)
      action = mdn.gaussian_mixture_approximate_mode(gm)
      if labels is not None:
        outputs['bc_loss'] = -jnp.mean(mdn.mixture_log_prob(
            gm, jnp.asarray(labels['action'], jnp.float32)))
    else:
      action = meta_data.multi_batch_apply(
          vision_layers.ImageFeaturesToPoseNet(
              num_outputs=self.action_size, name='a_func'), 3, fc_inputs)
      if labels is not None:
        outputs['bc_loss'] = jnp.mean(
            (action.astype(jnp.float32) -
             jnp.asarray(labels['action'], jnp.float32)) ** 2)
    outputs['inference_output'] = action
    return outputs


class VRGripperEnvVisionTrialModel(_FixedCountMetaModel):
  """Vision WTL trial/retrial model (ref :359-574)."""

  def __init__(self,
               action_size: int = 7,
               embed_loss_weight: float = 0.0,
               fc_embed_size: int = 32,
               ignore_embedding: bool = False,
               num_mixture_components: int = 1,
               **kwargs):
    super().__init__(**kwargs)
    self._action_size = action_size
    self._embed_loss_weight = embed_loss_weight
    self._fc_embed_size = fc_embed_size
    self._ignore_embedding = ignore_embedding
    self._num_mixture_components = num_mixture_components

  def _episode_feature_specification(self, mode: str) -> SpecStruct:
    """ref :384-397."""
    del mode
    return SpecStruct(
        image=TensorSpec((self._episode_length, 100, 100, 3), np.float32,
                         name='image0', data_format='jpeg'),
        gripper_pose=TensorSpec((self._episode_length, 14), np.float32,
                                name='world_pose_gripper'))

  def _episode_label_specification(self, mode: str) -> SpecStruct:
    """ref :399-409."""
    del mode
    return SpecStruct(
        action=TensorSpec((self._episode_length, self._action_size),
                          np.float32, name='action_world'),
        success=TensorSpec((self._episode_length, 1), np.float32,
                           name='success'))

  def create_network(self) -> nn.Module:
    return _VisionTrialNet(
        action_size=self._action_size,
        episode_length=self._episode_length,
        fc_embed_size=self._fc_embed_size,
        ignore_embedding=self._ignore_embedding,
        num_mixture_components=self._num_mixture_components,
        num_condition_samples_per_task=self._num_condition)

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    """ref :507-530."""
    bc_loss = inference_outputs['bc_loss']
    return bc_loss, SpecStruct(bc_loss=bc_loss)

  def model_eval_fn(self, variables, features, labels, inference_outputs,
                    mode: str) -> SpecStruct:
    loss, train_outputs = self.model_train_fn(
        variables, features, labels, inference_outputs, mode)
    metrics = SpecStruct(loss=loss)
    for key in train_outputs:
      metrics['mean_' + key] = train_outputs[key]
    return metrics

  def pack_features(self, state, prev_episode_data, timestep):
    """ref :553-574."""
    return pack_wtl_meta_features(
        state, prev_episode_data, timestep, self._episode_length,
        self._num_condition, vision=True)
