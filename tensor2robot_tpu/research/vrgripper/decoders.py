"""Action decoders for VRGripper BC models: MSE, MDN, MAF, discrete.

Parity targets:
  * MSEDecoder       /root/reference/research/vrgripper/mse_decoder.py:31
  * MAFDecoder       /root/reference/research/vrgripper/maf.py:72
  * DiscreteDecoder + bin helpers
                     /root/reference/research/vrgripper/discrete.py:37-143
  * (MDN decoding lives in layers/mdn.py, ref layers/mdn.py:129)

The reference decoders are stateful objects (``__call__`` builds the head,
``loss(labels)`` reads cached tensors). Functionally they become Flax
modules with one entry point::

    decoder(params_input, labels_action=None, rng=None)
      -> SpecStruct(action=..., [nll/logits/...], [loss=...])

``loss`` is returned alongside the action when labels are provided, so the
whole decode+loss runs inside the one jitted train step.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import maf as maf_lib
from tensor2robot_tpu.layers import mdn
from tensor2robot_tpu.specs.struct import SpecStruct


class MSEDecoder(nn.Module):
  """Plain linear head + mean squared error (ref mse_decoder.py:31)."""

  output_size: int

  @nn.compact
  def __call__(self, params_input, labels_action=None, rng=None) -> SpecStruct:
    predictions = nn.Dense(self.output_size, name='pose')(params_input)
    out = SpecStruct(action=predictions)
    if labels_action is not None:
      labels_action = jnp.asarray(labels_action, jnp.float32)
      out['loss'] = jnp.mean(
          (predictions.astype(jnp.float32) - labels_action) ** 2)
    return out


class MDNActionDecoder(nn.Module):
  """Gaussian-mixture head (ref layers/mdn.py:129 MDNDecoder).

  Action = approximate mixture mode (or a sample when ``rng`` is given);
  loss = mean NLL of the labels under the mixture.
  """

  output_size: int
  num_mixture_components: int = 1
  condition_sigmas: bool = False

  @nn.compact
  def __call__(self, params_input, labels_action=None, rng=None) -> SpecStruct:
    dist_params = mdn.MDNParamsLayer(
        num_alphas=self.num_mixture_components,
        sample_size=self.output_size,
        condition_sigmas=self.condition_sigmas,
        name='mdn_params')(params_input)
    gm = mdn.get_mixture_distribution(
        dist_params.astype(jnp.float32), self.num_mixture_components,
        self.output_size)
    if rng is not None:
      action = mdn.mixture_sample(gm, rng)
    else:
      action = mdn.gaussian_mixture_approximate_mode(gm)
    out = SpecStruct(action=action, dist_params=dist_params)
    if labels_action is not None:
      out['loss'] = mdn.mdn_loss(gm, jnp.asarray(labels_action, jnp.float32))
    return out


class MAFDecoder(nn.Module):
  """Masked-autoregressive-flow head (ref maf.py:72)."""

  output_size: int
  num_flows: int = 1
  hidden_layers: Tuple[int, ...] = (512, 512)

  @nn.compact
  def __call__(self, params_input, labels_action=None, rng=None) -> SpecStruct:
    dist = maf_lib.MAFDistribution(
        output_size=self.output_size, num_flows=self.num_flows,
        hidden_layers=self.hidden_layers, name='maf')
    value = (jnp.asarray(labels_action, jnp.float32)
             if labels_action is not None else None)
    sample, log_prob = dist(params_input, value=value, rng=rng)
    out = SpecStruct(action=sample)
    if log_prob is not None:
      # Average across batch and sequence (ref maf.py:100-103).
      out['loss'] = -jnp.mean(log_prob)
    return out


# -- discrete actions ---------------------------------------------------------


def get_discrete_bins(num_bins: int, output_min, output_max) -> np.ndarray:
  """[num_bins, action_dim] bin centers (ref discrete.py:37)."""
  output_min = np.asarray(output_min, np.float32)
  output_max = np.asarray(output_max, np.float32)
  bin_sizes = (output_max - output_min) / float(num_bins)
  return np.array([output_min + bin_sizes * (bin_i + 0.5)
                   for bin_i in range(num_bins)], dtype=np.float32)


def get_discrete_actions(logits: jnp.ndarray, action_size: int,
                         num_bins: int, bin_centers) -> jnp.ndarray:
  """Mode action per dimension from bin logits (ref discrete.py:56)."""
  leading = logits.shape[:-1]
  probabilities = jax.nn.softmax(
      logits.reshape((-1, action_size, num_bins)).astype(jnp.float32))
  one_hot = jax.nn.one_hot(jnp.argmax(probabilities, -1), num_bins)
  centers = jnp.asarray(np.transpose(bin_centers))  # [action_dim, num_bins]
  actions = jnp.sum(one_hot * centers, -1)
  return actions.reshape(leading + (action_size,))


def get_discrete_action_loss(logits: jnp.ndarray, action_labels: jnp.ndarray,
                             bin_centers, num_bins: int) -> jnp.ndarray:
  """Cross entropy against the nearest-bin one-hot (ref discrete.py:87)."""
  action_labels = jnp.asarray(action_labels, jnp.float32)[..., None, :]
  centers = jnp.asarray(bin_centers)  # [num_bins, action_dim]
  while centers.ndim < action_labels.ndim:
    centers = centers[None, ...]
  discrete_labels = jnp.argmin((action_labels - centers) ** 2, -2)
  one_hot = jax.nn.one_hot(discrete_labels, num_bins).reshape((-1, num_bins))
  logits = logits.reshape((-1, num_bins)).astype(jnp.float32)
  log_probs = jax.nn.log_softmax(logits)
  return -jnp.mean(jnp.sum(one_hot * log_probs, axis=-1))


class DiscreteDecoder(nn.Module):
  """Per-dimension discretized action head (ref discrete.py:112)."""

  output_size: int
  num_bins: int = 1
  output_min: Sequence[float] = ()
  output_max: Sequence[float] = ()

  @nn.compact
  def __call__(self, params_input, labels_action=None, rng=None) -> SpecStruct:
    bin_centers = get_discrete_bins(self.num_bins,
                                    np.asarray(self.output_min),
                                    np.asarray(self.output_max))
    logits = nn.Dense(self.output_size * self.num_bins,
                      name='action_logits')(params_input)
    action = get_discrete_actions(logits, self.output_size, self.num_bins,
                                  bin_centers)
    out = SpecStruct(action=action, action_logits=logits)
    if labels_action is not None:
      out['loss'] = get_discrete_action_loss(logits, labels_action,
                                             bin_centers, self.num_bins)
    return out
