"""Episode -> serialized-example converters for VRGripper replay data.

Parity target: /root/reference/research/vrgripper/episode_to_transitions.py
(make_fixed_length :45, episode_to_transitions_reacher :88,
episode_to_transitions_metareacher :108). tf.train.Example construction is
replaced by the dependency-free wire codec (data/wire.py), producing
byte-identical record framing.
"""

from __future__ import annotations

import collections
from typing import List, Optional

import numpy as np

from tensor2robot_tpu.data import wire


def make_fixed_length(input_list,
                      fixed_length: int,
                      always_include_endpoints: bool = True,
                      randomized: bool = True) -> Optional[list]:
  """Samples ``input_list`` down/up to ``fixed_length`` entries (ref :45).

  Returns None for episodes of length <= 2 (too short to subsample).
  """
  original_length = len(input_list)
  if original_length <= 2:
    return None
  if not randomized:
    indices = np.sort(np.mod(np.arange(fixed_length), original_length))
    return [input_list[i] for i in indices]
  if always_include_endpoints:
    endpoint_indices = np.array([0, original_length - 1])
    other_indices = 1 + np.random.choice(
        original_length - 2, fixed_length - 2, replace=True)
    indices = np.concatenate((endpoint_indices, other_indices), axis=0)
  else:
    indices = np.random.choice(original_length, fixed_length, replace=True)
  indices = np.sort(indices)
  return [input_list[i] for i in indices]


def episode_to_transitions_reacher(episode_data, is_demo: bool = False
                                   ) -> List[bytes]:
  """Reacher env transitions -> one serialized Example each (ref :88)."""
  transitions = []
  for transition in episode_data:
    obs_t, action, reward, obs_tp1, done, debug = transition
    del debug
    transitions.append(wire.build_example({
        'pose_t': np.asarray(obs_t, np.float32),
        'pose_tp1': np.asarray(obs_tp1, np.float32),
        'action': np.asarray(action, np.float32),
        'reward': np.asarray([reward], np.float32),
        'done': np.asarray([int(done)], np.int64),
        'is_demo': np.asarray([int(is_demo)], np.int64),
    }))
  return transitions


def episode_to_transitions_metareacher(episode_data) -> List[bytes]:
  """Meta-reacher episode -> ONE serialized SequenceExample (ref :108)."""
  context = {
      'is_demo': np.asarray([int(episode_data[0][-1]['is_demo'])], np.int64),
      'target_idx': np.asarray([episode_data[0][-1]['target_idx']], np.int64),
  }
  feature_lists = collections.defaultdict(list)
  for transition in episode_data:
    obs_t, action, reward, obs_tp1, done, debug = transition
    del debug
    feature_lists['pose_t'].append(np.asarray(obs_t, np.float32))
    feature_lists['pose_tp1'].append(np.asarray(obs_tp1, np.float32))
    feature_lists['action'].append(np.asarray(action, np.float32))
    feature_lists['reward'].append(np.asarray([reward], np.float32))
    feature_lists['done'].append(np.asarray([int(done)], np.int64))
  return [wire.build_sequence_example(context, dict(feature_lists))]
