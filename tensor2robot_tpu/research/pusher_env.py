"""2D pusher: a dynamics-bearing environment for the RL loop.

The reference's smoke-test env runs PyBullet headless
(/root/reference/research/pose_env/pose_env.py:56-84, DIRECT mode) so its
collect->train->eval cycle closes over real state transitions. PyBullet
cannot be installed in this build environment and the pose toy env's
numpy rasterizer is a one-step bandit, so this module supplies the
dynamics: a point object with MOMENTUM pushed around a walled arena under
FORCE actions, with process NOISE and inelastic wall CONTACT —
state-transition structure a policy must actually face
(tests/test_pusher.py asserts a trained critic policy beats random
through the full rl/collect_eval.py cycle).

Dynamics (dt-discretized, per step):
    v' = damping * v + dt * force_scale * clip(a, -1, 1) + noise
    p' = clip(p + dt * v', arena);  v' := 0 on the clipped axes (contact)
    reward = 1 - ||p' - goal|| / diameter          # in [0, 1]

The observation is the low-dim state (position, velocity, goal): the
vision stack is exercised by the QT-Opt systems test (tests/test_qtopt.py);
this env isolates DYNAMICS, keeping the learning-curve test minutes-fast.
Because reward depends on the post-step position, the best action depends
on the current VELOCITY, not just position — a policy that ignores
momentum measurably underperforms one that does not.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

import flax.linen as nn
import jax.numpy as jnp

from tensor2robot_tpu.data import wire
from tensor2robot_tpu.models.critic_model import CriticModel
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec

STATE_SIZE = 6    # position (2) + velocity (2) + goal (2)
ACTION_SIZE = 2
_DIAMETER = 2.0 * np.sqrt(2.0)


class PusherEnv:
  """Gym-style 2D pusher (reset() -> obs; step(a) -> obs, r, done, dbg)."""

  def __init__(self,
               episode_length: int = 8,
               dt: float = 0.25,
               damping: float = 0.85,
               force_scale: float = 1.6,
               noise_std: float = 0.02,
               seed: Optional[int] = None):
    self._episode_length = episode_length
    self._dt = dt
    self._damping = damping
    self._force_scale = force_scale
    self._noise_std = noise_std
    self._rng = np.random.RandomState(seed)
    self._p = np.zeros(2)
    self._v = np.zeros(2)
    self._goal = np.zeros(2)
    self._t = 0

  def _obs(self) -> np.ndarray:
    return np.concatenate([self._p, self._v, self._goal]).astype(np.float32)

  def reset(self) -> np.ndarray:
    self._p = self._rng.uniform(-0.8, 0.8, 2)
    self._v = np.zeros(2)
    self._goal = self._rng.uniform(-0.8, 0.8, 2)
    while np.linalg.norm(self._goal - self._p) < 0.5:
      self._goal = self._rng.uniform(-0.8, 0.8, 2)
    self._t = 0
    return self._obs()

  def step(self, action):
    action = np.clip(np.asarray(action, np.float64).ravel()[:2], -1.0, 1.0)
    self._v = (self._damping * self._v + self._dt * self._force_scale *
               action + self._rng.randn(2) * self._noise_std)
    p_new = self._p + self._dt * self._v
    clipped = np.clip(p_new, -1.0, 1.0)
    self._v[clipped != p_new] = 0.0   # inelastic wall contact
    self._p = clipped
    self._t += 1
    reward = 1.0 - np.linalg.norm(self._p - self._goal) / _DIAMETER
    done = self._t >= self._episode_length
    return self._obs(), float(reward), done, {}

  def close(self):
    pass


class PusherRandomPolicy:
  """Uniform-random forces (collect_eval_loop policy protocol)."""

  def __init__(self, seed: Optional[int] = None):
    self._rng = np.random.RandomState(seed)

  def reset(self):
    pass

  def restore(self) -> bool:
    return True

  def init_randomly(self) -> None:
    pass

  @property
  def global_step(self) -> int:
    return 0

  def sample_action(self, obs, explore_prob):
    del obs, explore_prob
    return self._rng.uniform(-1.0, 1.0, ACTION_SIZE), None


class PusherCriticPolicy:
  """Greedy-over-sampled-actions Q policy served from a predictor."""

  def __init__(self, predictor, num_samples: int = 128,
               seed: Optional[int] = None):
    self._predictor = predictor
    self._num_samples = num_samples
    self._rng = np.random.RandomState(seed)

  def reset(self):
    pass

  def restore(self) -> bool:
    return self._predictor.restore()

  def init_randomly(self) -> None:
    self._predictor.init_randomly()

  @property
  def global_step(self) -> int:
    return self._predictor.global_step

  def sample_action(self, obs, explore_prob):
    actions = self._rng.uniform(-1.0, 1.0,
                                (self._num_samples, ACTION_SIZE))
    states = np.tile(np.asarray(obs, np.float32)[None, :],
                     (self._num_samples, 1))
    out = self._predictor.predict({'state/obs': states,
                                   'action/force':
                                       actions.astype(np.float32)})
    q = np.asarray(out['q_predicted']).ravel()
    return actions[int(np.argmax(q))], {'q': float(q.max())}


def episode_to_transitions_pusher(episode_data) -> List[bytes]:
  """(obs, action, reward, obs_tp1, done, debug) -> transition Examples."""
  transitions = []
  for obs_t, action, reward, _obs_tp1, _done, _debug in episode_data:
    transitions.append(wire.build_example({
        'state': np.asarray(obs_t, np.float32).ravel(),
        'action': np.asarray(action, np.float32).ravel(),
        'reward': np.asarray([reward], np.float32),
    }))
  return transitions


class _PusherQNet(nn.Module):
  """MLP critic over concat(state, action) -> q in [0, 1]."""

  hidden: int = 64

  @nn.compact
  def __call__(self, features, mode: str = 'train', train: bool = False):
    x = jnp.concatenate(
        [jnp.asarray(features['state/obs'], jnp.float32),
         jnp.asarray(features['action/force'], jnp.float32)], axis=-1)
    for _ in range(2):
      x = nn.relu(nn.Dense(self.hidden)(x))
    logits = nn.Dense(1)(x)
    return {'q_logits': logits, 'q_predicted': nn.sigmoid(logits)}


class PusherCriticModel(CriticModel):
  """Q(s, a) regression against the env's in-[0,1] shaped reward."""

  def get_state_specification(self) -> SpecStruct:
    return SpecStruct(obs=TensorSpec((STATE_SIZE,), np.float32,
                                     name='state'))

  def get_action_specification(self) -> SpecStruct:
    return SpecStruct(force=TensorSpec((ACTION_SIZE,), np.float32,
                                       name='action'))

  def get_label_specification(self, mode: str) -> SpecStruct:
    del mode
    return SpecStruct(reward=TensorSpec((1,), np.float32, name='reward'))

  def create_network(self) -> nn.Module:
    return _PusherQNet()
