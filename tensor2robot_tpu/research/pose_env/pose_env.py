"""Duck pose prediction toy task.

Parity target: /root/reference/research/pose_env/pose_env.py:39-181
(PoseToyEnv + PoseEnvRandomPolicy). The reference renders a PyBullet duck on
a table from a random camera; the observation is a 64x64x3 image, the action
is the predicted (x, y) pose, reward is -||target - action||, episodes are
one step long. ``hidden_drift`` offsets the true pose from the rendered one
per task — solvable only by meta-adaptation.

This build has no PyBullet dependency: the scene (gray ground, brown table
top, yellow duck body + orange head indicating the yaw angle) is rendered
with a small numpy pinhole-projection rasterizer. The camera model matches
the reference's parameterization (look-at origin, distance 3, fov 30, random
yaw, pitch -30±10), so the learning problem — regress object pose from a
randomly-oriented camera view, camera fixed within a task — is preserved.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class PoseEnvRandomPolicy:
  """Uniform-random pose guesses, used for dataset generation (ref :40)."""

  def reset(self):
    pass

  def restore(self) -> bool:
    """Nothing to restore (collect_eval_loop polling protocol)."""
    return True

  def init_randomly(self) -> None:
    pass

  @property
  def global_step(self) -> int:
    return 0

  def sample_action(self, obs, explore_prob):
    del obs, explore_prob
    return np.random.uniform(low=-1., high=1., size=2), None


def _look_at_matrix(yaw_deg: float, pitch_deg: float, distance: float
                    ) -> Tuple[np.ndarray, np.ndarray]:
  """Camera rotation (world->cam) + position for a look-at-origin orbit."""
  yaw = np.deg2rad(yaw_deg)
  pitch = np.deg2rad(pitch_deg)
  # Camera position on the orbit sphere.
  eye = distance * np.array([
      np.cos(pitch) * np.sin(yaw),
      -np.cos(pitch) * np.cos(yaw),
      -np.sin(pitch),
  ])
  forward = -eye / np.linalg.norm(eye)           # towards the origin
  world_up = np.array([0.0, 0.0, 1.0])
  right = np.cross(forward, world_up)
  right /= max(np.linalg.norm(right), 1e-8)
  up = np.cross(right, forward)
  rotation = np.stack([right, up, forward])      # rows: cam axes in world
  return rotation, eye


class PoseToyEnv:
  """Predict object pose given the current image (ref PoseToyEnv :56).

  Observation: [height, width, 3] uint8 image, random camera per task.
  Action: predicted (x, y) pose. Reward: -||target_xy - action||_2.
  Episodes are single-step.

  Unlike the reference (whose reset() relies on external reset_task calls),
  ``reset()`` samples a fresh object pose each episode by default — the
  behavior every caller wants for dataset generation; the camera still only
  changes on ``reset_task()``. Pass ``new_pose_on_reset=False`` for the
  reference's literal semantics.
  """

  def __init__(self,
               render_mode: str = 'DIRECT',
               hidden_drift: bool = False,
               urdf_root: str = '',
               width: int = 64,
               height: int = 64,
               new_pose_on_reset: bool = True,
               seed: Optional[int] = None):
    del render_mode, urdf_root  # no GUI / asset files in the numpy renderer
    self._width, self._height = width, height
    self._hidden_drift = hidden_drift
    self._hidden_drift_xyz = None
    self._new_pose_on_reset = new_pose_on_reset
    self._rng = np.random.RandomState(seed)
    self._fov_deg = 30.0
    self._distance = 3.0
    self.reset_task()

  # -- task / pose sampling (ref :114-146) -----------------------------------

  def reset_task(self) -> None:
    self._reset_camera()
    if self._hidden_drift:
      self._hidden_drift_xyz = self._rng.uniform(low=-.3, high=.3, size=3)
      self._hidden_drift_xyz[2] = 0
    self.set_new_pose()

  def set_new_pose(self) -> None:
    self._target_pose = self._sample_pose()
    self._rendered_pose = self._target_pose.copy()
    if self._hidden_drift:
      self._target_pose = self._target_pose + self._hidden_drift_xyz

  def _sample_pose(self) -> np.ndarray:
    return np.array([
        self._rng.uniform(low=-.7, high=.7),
        self._rng.uniform(low=-.4, high=.4),
        self._rng.uniform(low=-180, high=180),
    ])

  def _reset_camera(self) -> None:
    self._cam_pitch = -30 + self._rng.uniform(-10, 10)
    self._cam_yaw = self._rng.uniform(-180, 180)

  # -- rendering -------------------------------------------------------------

  def _project(self, points: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """World points [N, 3] -> (pixel coords [N, 2], depth [N])."""
    rotation, eye = _look_at_matrix(self._cam_yaw, self._cam_pitch,
                                    self._distance)
    cam = (points - eye) @ rotation.T
    depth = np.maximum(cam[:, 2], 1e-6)
    focal = (self._height / 2.0) / np.tan(np.deg2rad(self._fov_deg) / 2.0)
    u = self._width / 2.0 + focal * cam[:, 0] / depth
    v = self._height / 2.0 - focal * cam[:, 1] / depth
    return np.stack([u, v], axis=1), depth

  def _splat(self, image, pixels, depth, radius_world, color) -> None:
    """Draws filled disks (radius scaled by 1/depth) into the image."""
    focal = (self._height / 2.0) / np.tan(np.deg2rad(self._fov_deg) / 2.0)
    ys, xs = np.mgrid[0:self._height, 0:self._width]
    for (u, v), z in zip(pixels, depth):
      r = max(focal * radius_world / z, 1.0)
      mask = (xs - u) ** 2 + (ys - v) ** 2 <= r ** 2
      image[mask] = color

  def _get_image(self) -> np.ndarray:
    image = np.full((self._height, self._width, 3), 178, np.uint8)  # sky/bg
    # Table top: a grid of brown splats over the tray area.
    gx, gy = np.meshgrid(np.linspace(-0.95, 0.95, 13),
                         np.linspace(-0.65, 0.65, 9))
    table = np.stack([gx.ravel(), gy.ravel(), np.full(gx.size, -0.02)],
                     axis=1)
    pixels, depth = self._project(table)
    self._splat(image, pixels, depth, 0.09, np.array([120, 85, 60], np.uint8))
    # Duck: yellow body at (x, y), orange head offset along the yaw angle.
    x, y, angle = self._rendered_pose
    heading = np.deg2rad(angle)
    body = np.array([[x, y, 0.05]])
    head = np.array([[x + 0.12 * np.cos(heading),
                      y + 0.12 * np.sin(heading), 0.12]])
    pixels, depth = self._project(body)
    self._splat(image, pixels, depth, 0.11, np.array([230, 200, 30], np.uint8))
    pixels, depth = self._project(head)
    self._splat(image, pixels, depth, 0.055, np.array([240, 140, 20], np.uint8))
    return image

  def get_observation(self) -> np.ndarray:
    return self._get_image()

  # -- env API ---------------------------------------------------------------

  def reset(self) -> np.ndarray:
    if self._new_pose_on_reset:
      self.set_new_pose()
    return self.get_observation()

  def step(self, action):
    """ref :176-181: single-step episode, distance reward."""
    action = np.asarray(action, np.float32)
    reward = -np.linalg.norm(action - self._target_pose[:2]).astype(np.float32)
    done = True
    debug = {'target_pose': self._target_pose[:2].astype(np.float32)}
    return self.get_observation(), float(reward), done, debug

  def close(self) -> None:
    pass
