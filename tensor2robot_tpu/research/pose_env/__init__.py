"""Pose prediction toy task: the end-to-end smoke-test workload."""

from tensor2robot_tpu.research.pose_env.pose_env import (
    PoseEnvRandomPolicy,
    PoseToyEnv,
)
from tensor2robot_tpu.research.pose_env.pose_env_maml_models import (
    PoseEnvRegressionModelMAML,
)
from tensor2robot_tpu.research.pose_env.pose_env_models import (
    DefaultPoseEnvContinuousPreprocessor,
    DefaultPoseEnvRegressionPreprocessor,
    PoseEnvContinuousMCModel,
    PoseEnvRegressionModel,
)
from tensor2robot_tpu.research.pose_env.episode_to_transitions import (
    episode_to_transitions_pose_toy,
)

__all__ = [
    'PoseEnvRegressionModelMAML',
    'DefaultPoseEnvContinuousPreprocessor',
    'DefaultPoseEnvRegressionPreprocessor',
    'PoseEnvContinuousMCModel',
    'PoseEnvRandomPolicy',
    'PoseEnvRegressionModel',
    'PoseToyEnv',
    'episode_to_transitions_pose_toy',
]
