"""MAML models for the duck pose task.

Parity target: /root/reference/research/pose_env/pose_env_maml_models.py:33-107
(PoseEnvRegressionModelMAML): regression MAML whose robot-time features pack
the conditioning demo episode next to the inference state, with zero-reward
dummy episodes masking the inner gradient step when no demo exists yet.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from tensor2robot_tpu.meta_learning.maml_model import MAMLRegressionModel
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.research.pose_env.pose_env_models import (
    PoseEnvRegressionModel,
)


class PoseEnvRegressionModelMAML(MAMLRegressionModel):
  """MAML regression for the duck task (ref :33)."""

  def __init__(self, base_model: Optional[PoseEnvRegressionModel] = None,
               **kwargs):
    super().__init__(base_model=base_model or PoseEnvRegressionModel(),
                     **kwargs)

  def _make_dummy_labels(self) -> dict:
    """Zero labels whose reward=0 masks the inner gradient (ref :36-45)."""
    label_spec = self._base_model.get_label_specification(ModeKeys.TRAIN)
    return {
        'target_pose': np.zeros(tuple(label_spec['target_pose'].shape),
                                np.float32),
        'reward': np.zeros(tuple(label_spec['reward'].shape), np.float32),
    }

  def pack_features(self, state, prev_episode_data, timestep) -> dict:
    """Packs demo episode + current state into the meta layout (ref :56).

    Missing demos become dummy zero-reward condition samples so the inner
    loop applies no gradient (weighted loss contributes zero).
    """
    del timestep
    if prev_episode_data:
      obs, action, reward = (prev_episode_data[0][0][0],
                             prev_episode_data[0][0][1],
                             prev_episode_data[0][0][2])
      cond_state = np.asarray(obs)
      cond_labels = {
          'target_pose': np.asarray(action, np.float32),
          'reward': np.asarray([2.0 * reward - 1.0], np.float32),
      }
    else:
      dummy = self._make_dummy_labels()
      cond_state = np.asarray(state)
      cond_labels = {'target_pose': dummy['target_pose'],
                     'reward': dummy['reward']}
    # [task=1, samples=1, ...] layout.
    expand = lambda x: np.asarray(x)[None, None]
    return {
        'condition/features/state': expand(cond_state),
        'condition/labels/target_pose': expand(cond_labels['target_pose']),
        'condition/labels/reward': expand(cond_labels['reward']),
        'inference/features/state': expand(np.asarray(state)),
    }
