"""Models for the duck pose prediction task.

Parity target: /root/reference/research/pose_env/pose_env_models.py:45-329
(DefaultPoseEnvContinuousPreprocessor, PoseEnvContinuousMCModel,
DefaultPoseEnvRegressionPreprocessor, PoseEnvRegressionModel). The slim conv
stacks become Flax modules over the shared vision_layers towers; uint8->f32
image conversion stays in the preprocessor, which runs INSIDE the jitted
step on device.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import vision_layers
from tensor2robot_tpu.models.critic_model import CriticModel
from tensor2robot_tpu.models.regression_model import RegressionModel
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.specs.algebra import flatten_spec_structure
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec


def _convert_image(image):
  """uint8 [0, 255] -> float32 [0, 1] (ref tf.image.convert_image_dtype)."""
  if jnp.issubdtype(jnp.asarray(image).dtype, jnp.floating):
    return jnp.asarray(image, jnp.float32)
  return jnp.asarray(image, jnp.float32) / 255.0


class DefaultPoseEnvContinuousPreprocessor(AbstractPreprocessor):
  """uint8 images on disk -> float32 for the critic (ref :45-92)."""

  def get_in_feature_specification(self, mode: str) -> SpecStruct:
    model_spec = flatten_spec_structure(
        self._model_feature_specification(mode))
    spec = SpecStruct()
    spec['state/image'] = TensorSpec.from_spec(
        model_spec['state/image'], dtype=np.uint8)
    spec['action/pose'] = model_spec['action/pose']
    return spec

  def get_in_label_specification(self, mode: str) -> SpecStruct:
    return flatten_spec_structure(self._model_label_specification(mode))

  def get_out_feature_specification(self, mode: str) -> SpecStruct:
    return flatten_spec_structure(self._model_feature_specification(mode))

  def get_out_label_specification(self, mode: str) -> SpecStruct:
    return flatten_spec_structure(self._model_label_specification(mode))

  def _preprocess_fn(self, features, labels, mode, rng=None):
    features['state/image'] = _convert_image(features['state/image'])
    return features, labels


class _QNetwork(nn.Module):
  """Conv state tower + broadcast action context -> scalar Q (ref :120-178)."""

  channels: int = 32

  @nn.compact
  def __call__(self, features, mode: str = 'train', train: bool = False):
    net = _convert_image(features['state/image'])
    for i in range(3):
      net = nn.Conv(self.channels, (3, 3), padding='SAME',
                    name='conv{}'.format(i))(net)
      net = nn.LayerNorm(name='norm{}'.format(i))(net)
      net = nn.relu(net)
    action = jnp.asarray(features['action/pose'], jnp.float32)
    action_context = nn.relu(nn.Dense(self.channels, name='action_fc')(action))
    net = net + action_context[:, None, None, :]
    net = net.reshape((net.shape[0], -1))
    for i, width in enumerate((100, 100)):
      net = nn.Dense(width, name='fc{}'.format(i))(net)
      net = nn.LayerNorm(name='fc_norm{}'.format(i))(net)
      net = nn.relu(net)
    q = nn.Dense(1, name='q_head')(net)
    return {'q_predicted': jnp.squeeze(q, -1)}


class PoseEnvContinuousMCModel(CriticModel):
  """Continuous Monte-Carlo Q model for the pose env (ref :96)."""

  def __init__(self, preprocessor_cls=DefaultPoseEnvContinuousPreprocessor,
               **kwargs):
    kwargs.setdefault('device_type', 'cpu')
    super().__init__(preprocessor_cls=preprocessor_cls, **kwargs)

  def get_state_specification(self) -> SpecStruct:
    return SpecStruct(image=TensorSpec(
        (64, 64, 3), np.float32, name='state/image', data_format='jpeg'))

  def get_action_specification(self) -> SpecStruct:
    return SpecStruct(pose=TensorSpec((2,), np.float32, name='pose'))

  def get_label_specification(self, mode: str) -> SpecStruct:
    del mode
    return SpecStruct(reward=TensorSpec((), np.float32, name='reward'))

  def create_network(self) -> nn.Module:
    return _QNetwork()

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    # MC regression on the (negative-distance) return, not log loss: the
    # pose env's rewards are not in [0, 1] (ref q_func + default loss).
    q = inference_outputs['q_predicted']
    target = jnp.asarray(labels['reward'], q.dtype).reshape(q.shape)
    loss = jnp.mean((q - target).astype(jnp.float32) ** 2)
    return loss, SpecStruct()

  def pack_features(self, state, context, timestep, actions) -> dict:
    """One observation + N candidate actions for CEM (ref :180-184)."""
    del context, timestep
    return {'state/image': np.expand_dims(state, 0),
            'action/pose': np.asarray(actions, np.float32)}


class DefaultPoseEnvRegressionPreprocessor(AbstractPreprocessor):
  """uint8 images on disk -> float32 for regression (ref :187-231)."""

  def get_in_feature_specification(self, mode: str) -> SpecStruct:
    model_spec = flatten_spec_structure(
        self._model_feature_specification(mode))
    spec = SpecStruct()
    spec['state'] = TensorSpec.from_spec(model_spec['state'], dtype=np.uint8)
    return spec

  def get_in_label_specification(self, mode: str) -> SpecStruct:
    return flatten_spec_structure(self._model_label_specification(mode))

  def get_out_feature_specification(self, mode: str) -> SpecStruct:
    return flatten_spec_structure(self._model_feature_specification(mode))

  def get_out_label_specification(self, mode: str) -> SpecStruct:
    return flatten_spec_structure(self._model_label_specification(mode))

  def _preprocess_fn(self, features, labels, mode, rng=None):
    features['state'] = _convert_image(features['state'])
    return features, labels


class _RegressionNetwork(nn.Module):
  """Vision tower -> spatial softmax keypoints -> pose head (ref a_func)."""

  action_size: int = 2

  @nn.compact
  def __call__(self, features, mode: str = 'train', train: bool = False):
    image = _convert_image(features['state'])
    feature_points, _ = vision_layers.ImagesToFeaturesNet(
        name='state_features')(image, train=train)
    estimated_pose = vision_layers.ImageFeaturesToPoseNet(
        num_outputs=self.action_size, name='pose_net')(feature_points)
    return {'inference_output': estimated_pose,
            'state_features': feature_points}


class PoseEnvRegressionModel(RegressionModel):
  """Image -> (x, y) pose regression (ref :235-329)."""

  def __init__(self, action_size: int = 2,
               preprocessor_cls=DefaultPoseEnvRegressionPreprocessor,
               **kwargs):
    kwargs.setdefault('device_type', 'cpu')
    super().__init__(preprocessor_cls=preprocessor_cls, **kwargs)
    self._action_size = action_size

  @property
  def action_size(self) -> int:
    return self._action_size

  def get_feature_specification(self, mode: str) -> SpecStruct:
    del mode
    return SpecStruct(state=TensorSpec(
        (64, 64, 3), np.float32, name='state/image', data_format='jpeg'))

  def get_label_specification(self, mode: str) -> SpecStruct:
    del mode
    return SpecStruct(
        target_pose=TensorSpec((self._action_size,), np.float32,
                               name='target_pose'),
        reward=TensorSpec((1,), np.float32, name='reward'))

  def create_network(self) -> nn.Module:
    return _RegressionNetwork(action_size=self._action_size)

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    """Reward-weighted MSE against the true pose (ref loss_fn :324).

    Matches tf.losses.mean_squared_error(weights=reward): the weighted
    squared error summed, normalized by the count of nonzero weights.
    """
    predictions = inference_outputs['inference_output']
    targets = jnp.asarray(labels['target_pose'], predictions.dtype)
    weights = jnp.asarray(labels['reward'], jnp.float32)
    squared = (predictions - targets).astype(jnp.float32) ** 2
    weighted = squared * weights  # weights broadcast [B, 1] over action dims
    num_present = jnp.maximum(
        jnp.sum(jnp.where(weights != 0, 1.0, 0.0) *
                jnp.ones_like(squared)), 1.0)
    loss = jnp.sum(weighted) / num_present
    return loss, SpecStruct()

  def pack_features(self, state, context, timestep) -> dict:
    del context, timestep
    return {'state': np.expand_dims(state, 0)}
