"""Episode data -> serialized transition Examples for the pose toy env.

Parity target: /root/reference/research/pose_env/episode_to_transitions.py:32
(episode_to_transitions_pose_toy): jpeg-encode the observation, store the
attempted pose, its reward, and the true target pose — a supervised
regression dataset written by the collect loop.
"""

from __future__ import annotations

from typing import List

import numpy as np

from tensor2robot_tpu.data import wire
from tensor2robot_tpu.utils import image as image_lib


def episode_to_transitions_pose_toy(episode_data) -> List[bytes]:
  """(obs, action, reward, obs_tp1, done, debug) tuples -> example bytes."""
  transitions = []
  for obs_t, action, reward, _obs_tp1, _done, debug in episode_data:
    features = {
        'state/image': image_lib.numpy_to_image_string(
            np.asarray(obs_t, np.uint8), 'jpeg'),
        'pose': np.asarray(action, np.float32).ravel(),
        'reward': np.asarray([reward], np.float32),
        'target_pose': np.asarray(debug['target_pose'], np.float32).ravel(),
    }
    transitions.append(wire.build_example(features))
  return transitions
