"""Legacy QT-Opt optimizer construction (hparams -> optax chain).

Parity target: /root/reference/research/qtopt/optimizer_builder.py:29-100
(``BuildOpt``) plus the hparam defaults injected by the model wrapper
(/root/reference/research/qtopt/t2r_models.py:82-93). Semantics preserved:

  * exponential-decay learning rate with ``staircase=True`` and
    ``decay_steps = examples_per_epoch / batch_size * num_epochs_per_decay``
    (ref optimizer_builder.py:66-74);
  * optimizer selection 'momentum' | 'rmsprop' | adam-fallback with the
    legacy hyperparameters (momentum doubles as adam beta1, ref :78-91);
  * ``use_avg_model_params`` — the reference wraps the optimizer in
    ``MovingAverageOptimizer`` whose swapping saver checkpoints averaged
    weights (ref :93-98). TPU-natively the average is an ``optax.ema``
    tracked in ``TrainState.avg_params`` (models/abstract_model.py), which
    eval/serving read; ``build_opt`` therefore returns only the gradient
    transformation and callers pass ``use_avg_model_params`` +
    ``model_weights_averaging`` to the model base class.
"""

from __future__ import annotations

from typing import Optional

import optax


def default_hparams(**overrides) -> dict:
  """The legacy QT-Opt hparams (ref t2r_models.py:82-93)."""
  hparams = dict(
      batch_size=32,
      examples_per_epoch=3000000,
      learning_rate=1e-4,
      learning_rate_decay_factor=0.999,
      model_weights_averaging=0.9999,
      momentum=0.9,
      num_epochs_per_decay=2.0,
      optimizer='momentum',
      rmsprop_decay=0.9,
      rmsprop_epsilon=1.0,
      adam_beta2=0.999,
      adam_epsilon=1e-8,
      use_avg_model_params=True,
  )
  hparams.update(overrides)
  return hparams


def build_learning_rate_schedule(hparams: dict) -> optax.Schedule:
  """Staircased exponential decay (ref optimizer_builder.py:63-74)."""
  decay_steps = int(hparams['examples_per_epoch'] / hparams['batch_size'] *
                    hparams['num_epochs_per_decay'])
  return optax.exponential_decay(
      init_value=hparams['learning_rate'],
      transition_steps=decay_steps,
      decay_rate=hparams['learning_rate_decay_factor'],
      staircase=True)


def build_opt(hparams: Optional[dict] = None) -> optax.GradientTransformation:
  """Constructs the legacy optimizer chain (ref BuildOpt :29-100).

  Returns an optax GradientTransformation; parameter averaging is NOT part
  of the chain (see module docstring).
  """
  if hparams is None:
    hparams = default_hparams()
  learning_rate = build_learning_rate_schedule(hparams)
  optimizer = hparams['optimizer']
  if optimizer == 'momentum':
    return optax.sgd(learning_rate, momentum=hparams['momentum'])
  if optimizer == 'rmsprop':
    # tf.train.RMSPropOptimizer(decay, momentum, epsilon) semantics:
    # uncentered second-moment accumulator + momentum on the scaled step.
    return optax.rmsprop(
        learning_rate,
        decay=hparams['rmsprop_decay'],
        momentum=hparams['momentum'],
        eps=hparams['rmsprop_epsilon'])
  return optax.adam(
      learning_rate,
      b1=hparams['momentum'],
      b2=hparams.get('adam_beta2', 0.999),
      eps=hparams.get('adam_epsilon', 1e-8))
