"""QT-Opt: vision-based grasping Q-learning (arXiv 1806.10293)."""

from tensor2robot_tpu.research.qtopt.networks import (
    Grasping44Network,
    NUM_SAMPLES,
)
from tensor2robot_tpu.research.qtopt.optimizer_builder import (
    build_opt,
    default_hparams,
)
from tensor2robot_tpu.research.qtopt.t2r_models import (
    DefaultGrasping44ImagePreprocessor,
    Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom,
    LegacyGraspingModelWrapper,
)

__all__ = [
    'DefaultGrasping44ImagePreprocessor',
    'Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom',
    'Grasping44Network',
    'LegacyGraspingModelWrapper',
    'NUM_SAMPLES',
    'build_opt',
    'default_hparams',
]
