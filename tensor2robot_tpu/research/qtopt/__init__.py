"""QT-Opt: vision-based grasping Q-learning (arXiv 1806.10293)."""

from tensor2robot_tpu.research.qtopt.networks import (
    Grasping44Network,
    NUM_SAMPLES,
    l2_regularization_loss,
)
from tensor2robot_tpu.research.qtopt.optimizer_builder import (
    build_learning_rate_schedule,
    build_opt,
    default_hparams,
)
from tensor2robot_tpu.research.qtopt.t2r_models import (
    CEM_ACTION_SIZE,
    DefaultGrasping44ImagePreprocessor,
    Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom,
    GraspingQNetwork,
    LegacyGraspingModelWrapper,
    pack_features_kuka_e2e,
)

__all__ = [
    'CEM_ACTION_SIZE',
    'DefaultGrasping44ImagePreprocessor',
    'Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom',
    'Grasping44Network',
    'GraspingQNetwork',
    'LegacyGraspingModelWrapper',
    'NUM_SAMPLES',
    'build_learning_rate_schedule',
    'build_opt',
    'default_hparams',
    'l2_regularization_loss',
    'pack_features_kuka_e2e',
]
