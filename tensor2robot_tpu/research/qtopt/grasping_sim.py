"""Synthetic visual grasping MDP with analytic Q*: the off-policy testbed.

The reference's QT-Opt numbers come from 580k real kuka grasps — data this
environment cannot replace. What it CAN do is make the off-policy
machinery *measurable*: a grasping-shaped MDP whose optimal Q-function is
known in closed form, so convergence benchmarks and tests have an exact
criterion instead of a saturating synthetic rule (the weakness VERDICT r4
item 3 called out in the supervised convergence field).

The MDP (grasp-descend semantics, matching the Grasping44 action layout of
t2r_models.py ACTION_DIM_LAYOUT):

  * State: gripper at height ``h`` above an object (``height_to_bottom``
    in the observation, drawn in the rendered camera frame).
  * ``close_gripper > 0.5``: the episode TERMINATES with reward
    ``1 if h <= threshold else 0`` (grasp attempted; movement ignored).
  * Otherwise the vertical component of ``world_vector`` descends the
    gripper: ``h' = clip(h - descent_scale * clip(wv_z, -1, 1), 0, h_max)``
    with reward 0, up to ``episode_length`` steps. TIMEOUT transitions are
    written with ``done=0`` (bootstrap through the time limit — timeouts
    are not environment terminals), the standard partial-episode fix.

Optimal values, with n(h) = ceil(max(0, h - threshold) / descent_scale):
    V*(h)            = gamma ** n(h)
    Q*(h, close)     = 1 if h <= threshold else 0
    Q*(h, no-close)  = gamma * V*(clip(h - descent_scale * wv_z, 0, h_max))

Learning Q* for n(h) = 2 states requires value to propagate through TWO
target-network generations — the benchmark cannot saturate before the
lagged-export machinery has turned over twice, by construction.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

from tensor2robot_tpu.data import wire
from tensor2robot_tpu.research.qtopt.t2r_models import (
    ACTION_DIM_LAYOUT,
)
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec
from tensor2robot_tpu.utils.image import numpy_to_image_string

# Constants chosen for BALANCE under random exploration: heights are
# ~uniform over [0, H_MAX] in steady state, so P(h <= THRESHOLD) ~ 0.4 —
# close-terminal positives and negatives arrive in comparable numbers.
# (The round-5 first cut used THRESHOLD=0.25/H_MAX=1.2: ~13% positives on
# a conjunction rule, and the full-scale critic regressed the dataset
# mean instead of the rule — measured, see docs/round5_notes.md.)
THRESHOLD = 0.5
DESCENT_SCALE = 0.35
H_MAX = 1.6
GAMMA = 0.8


def action_dim_offset(name: str) -> int:
  """Start offset of one ACTION_DIM_LAYOUT block in the flat CEM vector."""
  offset = 0
  for key, size in ACTION_DIM_LAYOUT:
    if key == name:
      return offset
    offset += size
  raise KeyError(name)


# Flat CEM-action indices, derived from the layout so a reordering of
# ACTION_DIM_LAYOUT cannot silently desynchronize the numpy env, the
# vectorized env (envs/grasping.py) and the actor's exploration
# (rl/loop.py) — all three import these.
WV_Z_INDEX = action_dim_offset('world_vector') + 2  # world_vector z
CLOSE_INDEX = action_dim_offset('close_gripper')
OPEN_INDEX = action_dim_offset('open_gripper')
TERMINATE_INDEX = action_dim_offset('terminate_episode')


def steps_to_grasp(h: float, threshold: float = THRESHOLD,
                   descent_scale: float = DESCENT_SCALE) -> int:
  return int(math.ceil(max(0.0, h - threshold) / descent_scale))


def optimal_value(h: float, gamma: float = GAMMA, **kwargs) -> float:
  return gamma ** steps_to_grasp(h, **kwargs)


def _action_vector(wv_z: float = 0.0, close: float = 0.0) -> np.ndarray:
  """8-dim CEM action per ACTION_DIM_LAYOUT with the used dims set."""
  action = np.zeros((8,), np.float32)
  action[WV_Z_INDEX] = wv_z
  action[CLOSE_INDEX] = close
  return action


def gradient_background(height: int, width: int) -> np.ndarray:
  """The camera frame's deterministic background, float32 [H, W, 3].

  Shared with the vectorized JAX port (envs/grasping.py): both envs
  render over the SAME host-computed constant, so the per-pixel parity
  contract reduces to the (pure, float32) scene drawing."""
  x = np.linspace(0, 1, width)
  y = np.linspace(0, 1, height)
  return (np.outer(y, x)[..., None]
          * np.array([140, 160, 180])).astype(np.float32)


class SimGraspingEnv:
  """Gym-style visual grasping env (reset() -> obs; step(a) -> o, r, d, i).

  Observations match the Grasping44 serving contract
  (t2r_models.pack_features_kuka_e2e): ``image`` uint8 [H, W, 3],
  ``gripper_closed`` and ``height_to_bottom`` scalars. ``info['terminal']``
  distinguishes a genuine grasp-attempt terminal from a timeout.

  ``safe_region``: ((y0, y1), (x0, x1)) pixel box guaranteed visible under
  every train-time random crop; scene content stays inside it so the
  crop never hides the task. Defaults to the 512x640 -> 472x472 band.
  """

  def __init__(self,
               height: int = 512,
               width: int = 640,
               episode_length: int = 3,
               threshold: float = THRESHOLD,
               descent_scale: float = DESCENT_SCALE,
               safe_region: Optional[Tuple[Tuple[int, int],
                                           Tuple[int, int]]] = None,
               noise_scale: float = 4.0,
               seed: Optional[int] = None):
    self._height = height
    self._width = width
    self._episode_length = episode_length
    self._threshold = threshold
    self._descent_scale = descent_scale
    self._noise_scale = float(noise_scale)
    if safe_region is None:
      if (height, width) == (512, 640):
        safe_region = ((40, 472), (168, 472))
      else:
        safe_region = ((0, height), (0, width))
    self._safe = safe_region
    self._rng = np.random.RandomState(seed)
    self._h = 0.0
    self._t = 0
    self._background = None

  @property
  def threshold(self) -> float:
    return self._threshold

  def _render(self, h: float) -> np.ndarray:
    """Camera-like frame: gradient + noise, object block, gripper at h."""
    height, width = self._height, self._width
    if self._background is None:
      self._background = gradient_background(height, width)
    img = self._background.copy()
    (y0, y1), (x0, x1) = self._safe
    band_h, band_w = y1 - y0, x1 - x0
    block = max(6, band_h // 14)
    cx = x0 + band_w // 2
    # Object sits on the "bin floor" at the bottom of the safe band.
    obj_y = y1 - 2 * block
    img[obj_y:obj_y + block, cx - block:cx + block] = (200, 40, 40)
    # Gripper height h in [0, H_MAX] maps to the band above the object.
    frac = min(max(h / H_MAX, 0.0), 1.0)
    grip_y = int(obj_y - block - frac * (band_h - 4 * block))
    grip_y = max(y0, grip_y)
    img[grip_y:grip_y + block, cx - block // 2:cx + block // 2] = (
        40, 200, 60)
    if self._noise_scale:
      img = img + self._rng.randn(height, width, 1) * self._noise_scale
    return np.clip(img, 0, 255).astype(np.uint8)

  def _obs(self) -> dict:
    return {'image': self._render(self._h),
            'gripper_closed': 0.0,
            'height_to_bottom': float(self._h)}

  def reset(self) -> dict:
    self._h = float(self._rng.uniform(0.1, 1.1))
    self._t = 0
    return self._obs()

  def step(self, action):
    action = np.asarray(action, np.float32).ravel()
    close = float(action[CLOSE_INDEX]) > 0.5
    self._t += 1
    if close:
      reward = 1.0 if self._h <= self._threshold else 0.0
      return self._obs(), reward, True, {'terminal': True}
    wv_z = float(np.clip(action[WV_Z_INDEX], -1.0, 1.0))
    self._h = float(np.clip(self._h - self._descent_scale * wv_z,
                            0.0, H_MAX))
    timeout = self._t >= self._episode_length
    return self._obs(), 0.0, timeout, {'terminal': False}

  def close(self):
    pass


class SimGraspingRandomPolicy:
  """Random exploration policy (collect_eval_loop policy protocol)."""

  def __init__(self, close_prob: float = 0.4, seed: Optional[int] = None):
    self._close_prob = close_prob
    self._rng = np.random.RandomState(seed)

  def reset(self):
    pass

  def restore(self) -> bool:
    return True

  def init_randomly(self) -> None:
    pass

  @property
  def global_step(self) -> int:
    return 0

  def sample_action(self, obs, explore_prob):
    del obs, explore_prob
    action = self._rng.uniform(-1.0, 1.0, 8).astype(np.float32)
    action[CLOSE_INDEX] = float(self._rng.rand() < self._close_prob)
    action[OPEN_INDEX] = float(self._rng.rand() < 0.5)
    action[TERMINATE_INDEX] = 0.0
    return action, None


# -- replay records ----------------------------------------------------------

# On-disk feature names for the off-policy extras. The state-side names
# follow the Grasping44 specs ('image_1', action key names).
NEXT_IMAGE_NAME = 'next/image_1'
NEXT_GRIPPER_CLOSED_NAME = 'next/gripper_closed'
NEXT_HEIGHT_NAME = 'next/height_to_bottom'
DONE_NAME = 'done'


def offpolicy_extra_feature_specs(image_spec: TensorSpec) -> SpecStruct:
  """Parsing specs for next-state + done, mirroring the raw image spec.

  Keyed so rl/offpolicy.split_offpolicy_batch renames ``next/<key>``
  straight back to critic in-spec keys.
  """
  extra = SpecStruct()
  extra['next/state/image'] = TensorSpec.from_spec(image_spec,
                                                   name=NEXT_IMAGE_NAME)
  extra['next/action/gripper_closed'] = TensorSpec(
      (1,), np.float32, name=NEXT_GRIPPER_CLOSED_NAME)
  extra['next/action/height_to_bottom'] = TensorSpec(
      (1,), np.float32, name=NEXT_HEIGHT_NAME)
  extra[DONE_NAME] = TensorSpec((1,), np.float32, name=DONE_NAME)
  return extra


def episode_to_transitions_grasping(episode_data,
                                    image_name: str = 'image_1',
                                    reward_name: str = 'grasp_success'
                                    ) -> List[bytes]:
  """(obs, action, reward, next_obs, done, info) -> transition Examples.

  Timeout transitions get ``done=0`` (module docstring): done reflects
  ``info['terminal']`` — whether the grasp was attempted — not whether
  the episode stopped.
  """
  transitions = []
  for obs, action, reward, next_obs, _done, info in episode_data:
    terminal = bool(info.get('terminal', False))
    example = {
        image_name: numpy_to_image_string(obs['image'], 'jpeg'),
        NEXT_IMAGE_NAME: numpy_to_image_string(next_obs['image'], 'jpeg'),
        NEXT_GRIPPER_CLOSED_NAME: np.asarray(
            [next_obs['gripper_closed']], np.float32),
        NEXT_HEIGHT_NAME: np.asarray(
            [next_obs['height_to_bottom']], np.float32),
        DONE_NAME: np.asarray([1.0 if terminal else 0.0], np.float32),
        reward_name: np.asarray([reward], np.float32),
    }
    flat_action = np.asarray(action, np.float32).ravel()
    offset = 0
    for key, size in ACTION_DIM_LAYOUT:
      example[key] = flat_action[offset:offset + size]
      offset += size
    example['gripper_closed'] = np.asarray(
        [obs['gripper_closed']], np.float32)
    example['height_to_bottom'] = np.asarray(
        [obs['height_to_bottom']], np.float32)
    transitions.append(wire.build_example(example))
  return transitions


def make_candidate_actions_fn(num_candidates: int):
  """Uniform CEM-style candidates for the Bellman max (rl/offpolicy.py).

  Returns all Grasping44 action keys flat [B*K, ...], state-major blocks;
  gripper status keys repeat the NEXT state's observed values.
  """
  import jax
  import jax.numpy as jnp

  def candidate_actions(rng, batch, next_features):
    k = num_candidates
    n = batch * k
    r_world, r_rot, r_disc = jax.random.split(rng, 3)
    out = {
        'action/world_vector': jax.random.uniform(
            r_world, (n, 3), minval=-1.0, maxval=1.0),
        'action/vertical_rotation': jax.random.uniform(
            r_rot, (n, 2), minval=-1.0, maxval=1.0),
    }
    disc = jax.random.bernoulli(r_disc, 0.5, (n, 3)).astype(jnp.float32)
    out['action/close_gripper'] = disc[:, 0:1]
    out['action/open_gripper'] = disc[:, 1:2]
    out['action/terminate_episode'] = jnp.zeros((n, 1), jnp.float32)
    for key in ('action/gripper_closed', 'action/height_to_bottom'):
      out[key] = jnp.repeat(
          jnp.asarray(next_features[key], jnp.float32).reshape(batch, 1),
          k, axis=0)
    return out

  return candidate_actions


# -- test-scale critic -------------------------------------------------------


def _small_image_preprocessor_cls(height: int, width: int):
  """A Grasping44-style jpeg-in/float-out preprocessor at test resolution."""
  from tensor2robot_tpu.modes import ModeKeys as _ModeKeys
  from tensor2robot_tpu.preprocessors.spec_transformation_preprocessor \
      import SpecTransformationPreprocessor

  class _SmallImagePreprocessor(SpecTransformationPreprocessor):

    def update_spec_transform(self, key, spec, mode):
      del mode
      if key == 'state/image':
        return TensorSpec.from_spec(spec, shape=(height, width, 3),
                                    dtype=np.uint8, data_format='jpeg')
      return spec

    def _preprocess_fn(self, features, labels, mode, rng=None):
      del mode, rng
      import jax.numpy as jnp
      features['state/image'] = jnp.asarray(
          features['state/image'], jnp.float32) / 255.0
      return features, labels

  return _SmallImagePreprocessor


def _build_sim_qnet():
  import flax.linen as nn
  import jax.numpy as jnp

  class SimQNet(nn.Module):
    """Tiny conv critic with the megabatch contract of GraspingQNetwork:
    the image tower runs once per STATE; flat [B*K] action rows reshape
    to [B, K, d] and score against the broadcast state embedding."""

    hidden: int = 64

    @nn.compact
    def __call__(self, features, mode: str = 'train', train: bool = False):
      del mode, train
      image = jnp.asarray(features['state/image'], jnp.float32)
      keys = [k for k, _ in ACTION_DIM_LAYOUT] + ['gripper_closed',
                                                  'height_to_bottom']
      params = jnp.concatenate(
          [jnp.asarray(features['action/' + key], jnp.float32).reshape(
              (jnp.asarray(features['action/' + key]).shape[0], -1))
           for key in keys], axis=-1)
      x = image
      for width in (8, 16):
        x = nn.relu(nn.Conv(width, (3, 3), strides=(2, 2))(x))
      x = x.reshape((x.shape[0], -1))
      x = nn.relu(nn.Dense(self.hidden)(x))
      batch = x.shape[0]
      if params.shape[0] != batch:
        params = params.reshape((batch, -1, params.shape[-1]))  # [B, K, d]
        x = jnp.broadcast_to(x[:, None, :],
                             (batch, params.shape[1], x.shape[-1]))
      h = jnp.concatenate([x, nn.relu(nn.Dense(self.hidden)(params))],
                          axis=-1)
      h = nn.relu(nn.Dense(self.hidden)(h))
      logits = nn.Dense(1)(h).reshape((-1,))
      return {'q_logits': logits, 'q_predicted': nn.sigmoid(logits)}

  return SimQNet


def make_sim_critic_model(height: int = 64, width: int = 80, **kwargs):
  """Test-scale CriticModel over SimGraspingEnv observations.

  Same spec keys and on-disk names as the Grasping44 flagship (so the
  replay/candidate helpers above work unchanged), tiny network, any
  resolution. Used by tests/test_offpolicy.py; the bench uses the real
  Grasping44 critic at full camera resolution.
  """
  from tensor2robot_tpu.models.critic_model import CriticModel

  class SimGraspingCriticModel(CriticModel):

    def get_state_specification(self) -> SpecStruct:
      return SpecStruct(image=TensorSpec((height, width, 3), np.float32,
                                         name='image_1'))

    def get_action_specification(self) -> SpecStruct:
      spec = SpecStruct()
      for key, size in ACTION_DIM_LAYOUT + (('gripper_closed', 1),
                                            ('height_to_bottom', 1)):
        spec[key] = TensorSpec((size,), np.float32, name=key)
      return spec

    def get_label_specification(self, mode: str) -> SpecStruct:
      del mode
      return SpecStruct(reward=TensorSpec((1,), np.float32,
                                          name='grasp_success'))

    def create_network(self):
      return _build_sim_qnet()()

  kwargs.setdefault('preprocessor_cls',
                    _small_image_preprocessor_cls(height, width))
  kwargs.setdefault('device_type', 'cpu')
  return SimGraspingCriticModel(**kwargs)


# -- held-out criterion ------------------------------------------------------


def build_ranking_pairs(env: SimGraspingEnv,
                        per_type: int = 32,
                        seed: int = 7,
                        gamma: float = GAMMA
                        ) -> Sequence[Tuple[dict, dict]]:
  """Margin-robust (better, worse) feature batches with known Q* order.

  Three pair families, in increasing bootstrap depth:
    1. aligned (n=0):  close-now (Q*=1)        >  ascend (Q*=gamma**2)
    2. one step out:   descend (Q*=gamma)      >  ascend (Q*=gamma**3)
    3. two steps out:  descend (Q*=gamma**2)   >  ascend (Q*=gamma**4)
  Families 2 and 3 compare two BOOTSTRAPPED arms (descend vs ascend at
  the same height): both sit at the sigmoid's ~0.5 until real value has
  propagated, so they cannot be ordered by the supervised terminal
  signal alone — family 3 orders correctly only after value has flowed
  through two lagged-target generations, the non-saturation guarantee.
  (A close-at-misaligned worse arm would be learnable from terminal
  transitions alone — Q driven to 0 supervised — and was rejected for
  exactly that reason.) Margins are robust to the candidate-limited max
  (random candidates descend ~0.3-0.4 per step instead of the exact
  0.4) and hold for any gamma in (0, 1).
  """
  del gamma  # orderings hold for any gamma in (0, 1)
  rng = np.random.RandomState(seed)
  thr, scale = env.threshold, env._descent_scale
  descend = _action_vector(wv_z=1.0, close=0.0)
  ascend = _action_vector(wv_z=-1.0, close=0.0)
  families = [
      (rng.uniform(0.02, thr - 0.05, per_type),
       _action_vector(wv_z=0.0, close=1.0), ascend),
      (rng.uniform(thr + 0.25 * scale, thr + 0.75 * scale, per_type),
       descend, ascend),
      (rng.uniform(thr + 1.3 * scale, thr + 1.8 * scale, per_type),
       descend, ascend),
  ]
  pairs = []
  for heights, better_action, worse_action in families:
    images = np.stack([env._render(h) for h in heights])
    better, worse = {}, {}
    for feats, action in ((better, better_action), (worse, worse_action)):
      feats['state/image'] = images
      offset = 0
      for key, size in ACTION_DIM_LAYOUT:
        feats['action/' + key] = np.tile(
            action[offset:offset + size], (per_type, 1))
        offset += size
      feats['action/gripper_closed'] = np.zeros((per_type, 1), np.float32)
      feats['action/height_to_bottom'] = np.asarray(
          heights, np.float32).reshape(per_type, 1)
    pairs.append((better, worse))
  return pairs
