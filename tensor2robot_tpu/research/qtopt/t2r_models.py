"""QT-Opt T2R models: the Grasping44 critic wrapped for the T2R stack.

Parity target: /root/reference/research/qtopt/t2r_models.py:50-405
(``pack_features_kuka_e2e`` :50, ``LegacyGraspingModelWrapper`` :66,
``DefaultGrasping44ImagePreprocessor`` :246, the E2E open/close/terminate
model :316). The TF1 responsibilities map as:

  * legacy hparams + BuildOpt optimizer (ref :82-100) -> ``optimizer_builder``
    optax chain; MovingAverageOptimizer/swapping-saver becomes
    ``use_avg_model_params`` EMA in TrainState (eval/serve read averaged
    params), see optimizer_builder.py docstring.
  * ``q_func`` building the slim graph (ref :143-162,:370-397) -> a Flax
    module (``GraspingQNetwork``) extracting image + grasp params from the
    spec-validated feature struct and running ``Grasping44Network``.
  * slim REGULARIZATION_LOSSES picked up by tf.losses.get_total_loss()
    (ref model_train_fn :233-243) -> explicit ``l2_regularization_loss``
    added to the sigmoid-cross-entropy grasp loss.
  * CEM action tiling via contrib_seq2seq.tile_batch (ref networks.py:520-527,
    concat_axis=2 in PREDICT :380-385) -> the action megabatch: candidate
    actions arrive flat ``[B*action_batch, d]``, are reshaped to
    ``[B, action_batch, d]``, and the image tower runs ONCE per state —
    only the embedding is tiled, so the MXU sees one large fused batch.

The preprocessor takes 512x640 uint8 camera images (jpeg on disk), random-
crops (train) or center-crops (eval/predict) to 472x472, converts to [0,1]
float and applies the paper's photometric distortions — all inside the jitted
step on device (the reference does this on host CPU in tf.data).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from tensor2robot_tpu.models import abstract_model
from tensor2robot_tpu.models.critic_model import CriticModel
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.preprocessors import image_transformations
from tensor2robot_tpu.preprocessors import pallas_crop
from tensor2robot_tpu.preprocessors.spec_transformation_preprocessor import (
    SpecTransformationPreprocessor,
)
from tensor2robot_tpu.research.qtopt import networks
from tensor2robot_tpu.research.qtopt import optimizer_builder
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec

INPUT_SHAPE = (512, 640, 3)
TARGET_SHAPE = (472, 472)

# Flat [N, 10] action-vector layout used by pack_features_kuka_e2e: the
# first 8 dims are CEM-sampled controls, the last 2 are gripper status
# carried in the action spec (ref get_action_specification :341-364).
ACTION_DIM_LAYOUT = (
    ('world_vector', 3),
    ('vertical_rotation', 2),
    ('close_gripper', 1),
    ('open_gripper', 1),
    ('terminate_episode', 1),
)
CEM_ACTION_SIZE = 8  # world_vector + vertical_rotation + 3 discrete controls


def pack_features_kuka_e2e(t2r_model, state, context, timestep, actions
                           ) -> Dict[str, np.ndarray]:
  """Packs one observation + N candidate actions for the CEM predictor.

  The reference's implementation is stripped from the OSS release
  (ref t2r_models.py:50-61 raises NotImplementedError); this provides the
  behavior its callers (CEM policies, ref policies.py:139-172) require.

  Args:
    t2r_model: the model (unused; kept for the reference pack_fn signature).
    state: observation dict with 'image' (uint8 [512, 640, 3] camera frame),
      'gripper_closed' and 'height_to_bottom' scalars.
    context: unused.
    timestep: unused.
    actions: [N, 8] CEM samples laid out per ACTION_DIM_LAYOUT.

  Returns:
    Numpy feed dict matching the preprocessor's PREDICT in-spec: the raw
    image once (batch 1; the device-side preprocessor center-crops it) and
    the N candidate actions.
  """
  del t2r_model, context, timestep
  actions = np.asarray(actions, np.float32)
  num_samples = actions.shape[0]
  features = {'state/image': np.expand_dims(np.asarray(state['image']), 0)}
  offset = 0
  for key, size in ACTION_DIM_LAYOUT:
    features['action/' + key] = actions[:, offset:offset + size]
    offset += size
  for key in ('gripper_closed', 'height_to_bottom'):
    features['action/' + key] = np.full(
        (num_samples, 1), np.float32(state[key]))
  return features


class GraspingQNetwork(nn.Module):
  """Feature-struct adapter around ``Grasping44Network``.

  Extracts the grasp image and concatenates the action features (in the
  reference's ``grasp_model_input_keys`` order, networks.py:637), handling
  the PREDICT-mode action megabatch (see module docstring).
  """

  grasp_param_keys: Tuple[str, ...] = networks.E2E_GRASP_PARAM_KEYS
  grasp_param_names: Optional[Dict[str, Tuple[int, int]]] = None
  dtype: jnp.dtype = jnp.float32
  network_kwargs: Optional[Dict[str, Any]] = None

  @nn.compact
  def __call__(self, features, mode: str = ModeKeys.TRAIN,
               train: bool = False):
    image = jnp.asarray(features['state/image'])
    grasp_params = jnp.concatenate(
        [jnp.asarray(features['action/' + key], jnp.float32).reshape(
            (jnp.asarray(features['action/' + key]).shape[0], -1))
         for key in self.grasp_param_keys], axis=-1)
    batch = image.shape[0]
    if grasp_params.shape[0] != batch:
      # CEM megabatch: N candidate actions per state arrive flat [B*A, d].
      grasp_params = grasp_params.reshape(
          (batch, -1, grasp_params.shape[-1]))
    endpoints = networks.Grasping44Network(
        grasp_param_names=self.grasp_param_names, dtype=self.dtype,
        name='grasping44', **(self.network_kwargs or {}))(
            image, grasp_params, train=train)
    q_predicted = endpoints['predictions']
    q_logits = endpoints['logits']
    if q_logits.ndim > 1 and q_logits.shape[-1] == 1:
      q_logits = jnp.squeeze(q_logits, -1)
    # Megabatch outputs [B, A] flatten back to the caller's [B*A] layout.
    outputs = SpecStruct(
        q_predicted=q_predicted.reshape((-1,)),
        q_logits=q_logits.reshape((-1,)))
    outputs['pool2'] = endpoints['pool2']
    outputs['final_conv'] = endpoints['final_conv']
    return outputs


class DefaultGrasping44ImagePreprocessor(SpecTransformationPreprocessor):
  """The default Grasping44 image preprocessor (ref t2r_models.py:246-312).

  On disk: 512x640 uint8 jpeg frames. For the model: 472x472 float32 in
  [0, 1], randomly cropped in TRAIN (center otherwise) with optional
  photometric distortions — which, like the reference's
  ApplyPhotometricImageDistortions defaults (image_transformations.py:182),
  are ALL OFF unless configured. Pure JAX on device; the crop runs on the
  uint8 frame so the float conversion and any distortions only touch the
  472x472 window (1.47x less elementwise work + HBM traffic than
  converting the full 512x640 frame first).
  """

  def __init__(self, *args, distortion_kwargs: Optional[dict] = None,
               use_fused_crop: Optional[bool] = None, **kwargs):
    """``distortion_kwargs`` forward to
    apply_photometric_image_distortions (e.g. {'random_brightness': True,
    'random_noise_level': 0.05}); default empty == reference defaults.

    ``use_fused_crop``: route the TRAIN crop+convert through the fused
    Pallas pass (``preprocessors/pallas_crop.py``) instead of the vmapped
    dynamic-slice + separate float convert. Numerics match the XLA path
    to 1 ulp with identical crop-offset sampling — but measured in the
    FULL batch-512 train step the kernel is ~3% SLOWER (183.6/180.3 ms
    f32/bf16-out vs 178.4 ms; docs/performance.md "Measured dead ends")
    despite being 7.5x faster in isolation: XLA fuses the convert into
    neighboring ops and the opaque pallas_call re-introduces a fusion
    barrier + conv1-input relayout. Default (``None``) therefore resolves
    to OFF; the flag stays for pipelines where the crop is NOT adjacent
    to a large fusible program.
    """
    super().__init__(*args, **kwargs)
    self._distortion_kwargs = dict(distortion_kwargs or {})
    self._use_fused_crop = use_fused_crop

  def update_spec_transform(self, key: str, spec: TensorSpec,
                            mode: str) -> TensorSpec:
    del mode
    if key == 'state/image':
      return TensorSpec.from_spec(
          spec, shape=INPUT_SHAPE, dtype=np.uint8, data_format='jpeg')
    return spec

  def _preprocess_fn(self, features, labels, mode: str, rng=None):
    image = jnp.asarray(features['state/image'])
    if mode == ModeKeys.TRAIN:
      if rng is None:
        raise ValueError('TRAIN-mode preprocessing requires an rng key.')
      crop_rng, distort_rng = jax.random.split(jnp.asarray(rng))
      # Default OFF: measured slower inside the full step (see __init__).
      use_fused = bool(self._use_fused_crop) and (
          image.dtype == jnp.uint8 and pallas_crop.supported(image.shape))
      if use_fused:
        offsets = image_transformations.random_crop_offsets(
            crop_rng, image.shape[0], image.shape[1:3], TARGET_SHAPE)
        image = pallas_crop.fused_crop_convert(image, offsets, TARGET_SHAPE)
      else:
        image = image_transformations.random_crop_images(
            crop_rng, [image], TARGET_SHAPE)[0]
        image = jnp.asarray(image, jnp.float32) / 255.0
      if self._distortion_kwargs:
        image = image_transformations.apply_photometric_image_distortions(
            distort_rng, [image], **self._distortion_kwargs)[0]
    else:
      image = image_transformations.center_crop_images(
          [image], TARGET_SHAPE)[0]
      image = jnp.asarray(image, jnp.float32) / 255.0
    features['state/image'] = image
    return features, labels


class LegacyGraspingModelWrapper(CriticModel):
  """T2R wrapper around the Grasping44 network family (ref :66-243).

  Subclasses declare ``legacy_network_kwargs``/state/action specs; training
  uses the legacy optimizer stack (momentum + staircase exponential decay +
  parameter averaging) via ``optimizer_builder.build_opt``.
  """

  def __init__(self,
               loss_function: Optional[Callable] = None,
               learning_rate: float = 1e-4,
               model_weights_averaging: float = 0.9999,
               momentum: float = 0.9,
               export_batch_size: int = 1,
               use_avg_model_params: bool = True,
               learning_rate_decay_factor: float = 0.999,
               action_batch_size: Optional[int] = None,
               preprocessor_cls=DefaultGrasping44ImagePreprocessor,
               optimizer_override: Optional[Callable] = None,
               **kwargs):
    """Hparam defaults mirror ref t2r_models.py:69-102.

    ``optimizer_override``: zero-arg optax factory replacing the legacy
    momentum + staircase-decay stack (e.g. ``lambda: optax.adam(3e-3)``)
    — for workloads that are not reproducing the paper's 2018 training
    recipe, such as the off-policy convergence benchmark, where adaptive
    steps learn action-conditional rules ~an order of magnitude faster
    (measured, docs/round5_notes.md).
    """
    self.hparams = optimizer_builder.default_hparams(
        learning_rate=learning_rate,
        learning_rate_decay_factor=learning_rate_decay_factor,
        model_weights_averaging=model_weights_averaging,
        momentum=momentum,
        use_avg_model_params=use_avg_model_params)
    self._loss_function = loss_function
    self._export_batch_size = export_batch_size
    self._network_kwargs = dict(kwargs.pop('network_kwargs', {}))
    super().__init__(
        action_batch_size=action_batch_size,
        preprocessor_cls=preprocessor_cls,
        create_optimizer_fn=(optimizer_override or
                             (lambda: optimizer_builder.build_opt(
                                 self.hparams))),
        use_avg_model_params=use_avg_model_params,
        avg_model_params_decay=model_weights_averaging,
        **kwargs)

  @property
  def legacy_network_kwargs(self) -> dict:
    """Constructor kwargs for Grasping44Network (ref legacy_model_class)."""
    return dict(self._network_kwargs)

  def get_label_specification(self, mode: str) -> SpecStruct:
    """ref :125-130 — grasp_success served as the 'reward' label."""
    del mode
    return SpecStruct(reward=TensorSpec(
        (1,), np.float32, name='grasp_success'))

  @property
  def l2_regularization_scale(self) -> float:
    return self.legacy_network_kwargs.get(
        'l2_regularization', networks.Grasping44Network.l2_regularization)

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    """Grasp cross-entropy + l2 weight decay (ref :233-243).

    The reference's tf.losses.get_total_loss() sums the log loss with slim's
    REGULARIZATION_LOSSES; here both terms are explicit.
    """
    q_logits = inference_outputs['q_logits']
    targets = jnp.asarray(labels[self.reward_key],
                          jnp.float32).reshape(q_logits.shape)
    if self._loss_function is not None:
      grasp_loss = self._loss_function(
          targets, inference_outputs[self.q_key])
    else:
      grasp_loss = jnp.mean(optax.sigmoid_binary_cross_entropy(
          q_logits.astype(jnp.float32), targets))
    l2_loss = networks.l2_regularization_loss(
        variables['params'], self.l2_regularization_scale)
    return grasp_loss + l2_loss, SpecStruct(grasp_loss=grasp_loss,
                                            l2_loss=l2_loss)

  def create_export_outputs_fn(self, features, inference_outputs, mode: str):
    del features, mode
    return SpecStruct(q_predicted=inference_outputs['q_predicted'],
                      q_logits=inference_outputs['q_logits'])

  def predict_step(self, state, features):
    """No state tiling: the network runs the action megabatch internally
    (image tower once per state; ref networks.py:520-527)."""
    return abstract_model.AbstractT2RModel.predict_step(self, state, features)


def _tile_scalar(value, num_samples: int):
  return jnp.broadcast_to(jnp.asarray(value, jnp.float32).reshape(1, 1),
                          (num_samples, 1))


class Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom(
    LegacyGraspingModelWrapper):
  """The QT-Opt flagship critic (ref :316-404).

  Controls gripper open/close/terminate with gripper status + height to
  bottom carried in the state-conditioned action vector. The grasp-param
  embedding uses the per-block dense layout of the reference E2E network
  (networks.py:736-744).
  """

  def get_state_specification(self) -> SpecStruct:
    """ref :336-339."""
    return SpecStruct(image=TensorSpec(
        TARGET_SHAPE + (3,), np.float32, name='image_1'))

  def get_action_specification(self) -> SpecStruct:
    """ref :341-364."""
    spec = SpecStruct()
    for key, size in ACTION_DIM_LAYOUT + (('gripper_closed', 1),
                                          ('height_to_bottom', 1)):
      spec[key] = TensorSpec((size,), np.float32, name=key)
    return spec

  def create_network(self) -> nn.Module:
    return GraspingQNetwork(
        grasp_param_keys=networks.E2E_GRASP_PARAM_KEYS,
        grasp_param_names=networks.E2E_GRASP_PARAM_NAMES,
        dtype=jnp.dtype(self.compute_dtype),
        network_kwargs=self.legacy_network_kwargs or None)

  def pack_features(self, *policy_inputs):
    """ref :399-400."""
    return pack_features_kuka_e2e(self, *policy_inputs)

  def make_on_device_select_action(self,
                                   cem_samples: int = 64,
                                   cem_iters: int = 3,
                                   num_elites: int = 10):
    """Builds the one-dispatch CEM action selector (DeviceCEMPolicy).

    The reference's CEM loop round-trips host<->device per iteration
    (policies.py:139-172: numpy CEM calling session.run 3x); here the
    ENTIRE loop — preprocessing, the lax.scan of CEM iterations, each
    scoring 64 candidates through the megabatch critic — is one jitted
    XLA program, so a robot action costs one dispatch and one image
    upload.

    Returns ``select(variables, state_dict, rng) -> (action [8], q)``
    with ``state_dict`` = {'image' uint8 [512, 640, 3], 'gripper_closed',
    'height_to_bottom'} and ``q`` the selected action's Q-value.
    """
    from tensor2robot_tpu.utils import cross_entropy

    def select(variables, state, rng):
      # Same serving semantics as every other path: EMA-averaged params
      # when configured (TrainState.variables), and the model's OWN
      # preprocessor for the predict-mode image transform.
      variables = dict(variables)
      avg_params = variables.pop('avg_params', None)
      if self.use_avg_model_params and avg_params is not None:
        variables['params'] = avg_params
      placeholder = SpecStruct()
      placeholder['state/image'] = jnp.asarray(state['image'])[None]
      for key, size in ACTION_DIM_LAYOUT:
        placeholder['action/' + key] = jnp.zeros((1, size), jnp.float32)
      for key in ('gripper_closed', 'height_to_bottom'):
        placeholder['action/' + key] = _tile_scalar(state[key], 1)
      processed, _ = self.preprocessor.preprocess(
          placeholder, None, ModeKeys.PREDICT, rng=None)
      image = processed['state/image']

      def objective(samples):
        features = SpecStruct()
        features['state/image'] = image
        offset = 0
        for key, size in ACTION_DIM_LAYOUT:
          features['action/' + key] = samples[:, offset:offset + size]
          offset += size
        for key in ('gripper_closed', 'height_to_bottom'):
          features['action/' + key] = _tile_scalar(state[key],
                                                   samples.shape[0])
        outputs, _ = self.inference_network_fn(
            variables, features, None, ModeKeys.PREDICT, None)
        return outputs['q_predicted']

      _, _, best = cross_entropy.jax_normal_cem(
          objective, jnp.zeros((CEM_ACTION_SIZE,), jnp.float32),
          jnp.ones((CEM_ACTION_SIZE,), jnp.float32), rng,
          num_samples=cem_samples, num_elites=num_elites,
          num_iterations=cem_iters)
      # The elite Q for per-step monitoring (run_env reads debug['q']).
      return best, objective(best[None])[0]

    return select

  def serving_feature_spec(self, image_shape=(512, 640, 3)):
    """Per-REQUEST feature contract for the serving layer (ISSUE 8).

    ``{name: (shape, dtype)}`` with no batch dim — what one
    ``SelectAction`` request carries and what ``PolicyServer`` validates
    and pads. ``image_shape`` is the RAW camera frame (the selector's
    own preprocessor crops to TARGET_SHAPE on device), so it is a
    deployment knob, not a model constant. ``bin/t2r_serve`` derives
    its spec and AOT shapes from this hook; any model exposing it plus
    ``make_batched_select_action`` serves through the generic path.
    """
    return {
        'image': (tuple(image_shape), np.uint8),
        'gripper_closed': ((), np.float32),
        'height_to_bottom': ((), np.float32),
    }

  def make_batched_select_action(self,
                                 cem_samples: int = 64,
                                 cem_iters: int = 3,
                                 num_elites: int = 10):
    """The serving megabatch program: B independent CEM selects, one
    dispatch (ISSUE 8).

    ``vmap`` of :meth:`make_on_device_select_action` over a leading
    state-batch dim — each row runs its own full CEM loop (its own
    ``cem_samples x cem_iters`` critic megabatch), so a PolicyServer
    batch of B coalesced robot requests is ONE XLA program scoring
    ``B * cem_samples`` candidates per iteration on the MXU.

    Returns ``batch_select(variables, states, seed) -> outputs`` with
    ``states`` = {'image' uint8 [B, 512, 640, 3], 'gripper_closed' [B],
    'height_to_bottom' [B]}, ``seed`` a uint32 scalar (each row gets
    ``fold_in(seed, row)``), and outputs {'action' [B, 8], 'q' [B]} —
    the (variables, features, seed) contract
    ``serving.PolicyServer`` batches through and
    ``serving.artifact.load_or_compile`` AOT-compiles.
    """
    import jax

    select = self.make_on_device_select_action(
        cem_samples=cem_samples, cem_iters=cem_iters,
        num_elites=num_elites)
    batched = jax.vmap(select, in_axes=(None, 0, 0))

    def batch_select(variables, states, seed):
      batch = jax.tree_util.tree_leaves(states)[0].shape[0]
      keys = jax.vmap(
          lambda i: jax.random.fold_in(jax.random.PRNGKey(seed), i))(
              jnp.arange(batch, dtype=jnp.uint32))
      actions, q = batched(variables, dict(states), keys)
      return {'action': actions, 'q': q}

    return batch_select
