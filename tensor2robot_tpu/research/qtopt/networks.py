"""QT-Opt grasping Q-networks (the Grasping44 PNN family).

Parity target: /root/reference/research/qtopt/networks.py:44-760
(GraspingModel, Grasping44FlexibleGraspParams :304, the E2E open/close/
terminate variant :623). The 19-layer conv architecture (NUM_LAYERS :35):

  conv1_1 64x6x6/2 -> bn(noscale) relu -> pool 3x3/3
  conv2..7 64x5x5 SAME (+bn relu) -> pool 3x3/3
  grasp params: per-block Dense 256 summed -> bn(noscale) relu
                -> Dense 64 (+bn relu) -> broadcast-add as [*,1,1,64] context
  conv8..13 64x3x3 SAME (+bn relu) -> pool 2x2/2
  conv14..16 64x3x3 VALID (+bn relu) -> flatten -> fc 64 x2 -> logit

TPU-first notes:
  * The CEM action-megabatch trick is preserved (ref :419-427, :520-527):
    with ``grasp_params`` of rank 3 [batch, action_batch, d], the image
    tower runs ONCE per state and only the embedding is tiled across the
    action batch — the MXU sees one large fused batch for the post-merge
    convs.
  * ``dtype`` selects the activations dtype (bfloat16 on TPU); the logit
    head and batch-norm statistics stay float32.
  * l2 regularization (ref slim weights_regularizer :438) is returned as
    an explicit ``l2_regularization_loss`` endpoint, added to the training
    loss by the model wrapper (the slim REGULARIZATION_LOSSES analog).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu.layers.pooling import max_pool

@jax.custom_jvp
def _schedule_barrier(x):
  """``optimization_barrier`` that stays differentiable on jax 0.4.x.

  The barrier is the identity — it only pins XLA scheduling — but older
  jax ships no AD rule for it, and eval-mode activations still get
  differentiated (e.g. actor gradients through a frozen Q-network, the
  stem-rewrite parity tests). Tangents pass straight through; the
  primal keeps the barrier, so the fusion guard holds wherever it runs.
  """
  return jax.lax.optimization_barrier(x)


@_schedule_barrier.defjvp
def _schedule_barrier_jvp(primals, tangents):
  (x,), (dx,) = primals, tangents
  return _schedule_barrier(x), dx


def _register_barrier_batch_rule() -> None:
  """``optimization_barrier`` also ships no vmap rule on jax 0.4.x.

  The barrier is elementwise identity, so batching it is the barrier on
  the batched operands with the batch dims passed straight through.
  Needed by the serving megabatch program (ISSUE 8):
  ``make_batched_select_action`` vmaps the CEM selector — and the Q
  tower under it — over the request batch. Registered at import, next
  to the AD rule above, with the same degrade-to-no-op posture when the
  internals move.
  """
  try:
    from jax._src.lax import lax as _lax_internal
    from jax.interpreters import batching as _batching
    prim = _lax_internal.optimization_barrier_p
  except (ImportError, AttributeError):  # newer jax: rule ships built-in
    return
  if prim in _batching.primitive_batchers:
    return

  def _rule(args, dims):
    return prim.bind(*args), list(dims)

  _batching.primitive_batchers[prim] = _rule


_register_barrier_batch_rule()


NUM_LAYERS = 19
BATCH_SIZE = 64
# Action samples when estimating max_a Q(s, a) (ref :37-41).
NUM_SAMPLES = 100

# grasp_param block layout of the E2E variant (ref networks.py:736-744):
# name -> (offset, size) into the concatenated grasp params vector.
E2E_GRASP_PARAM_NAMES = {
    'fcgrasp_wv': (0, 3),
    'fcgrasp_vr': (3, 2),
    'fcgrasp_gripper_close': (5, 1),
    'fcgrasp_gripper_open': (6, 1),
    'fcgrasp_terminate_episode': (7, 1),
    'fcgrasp_gripper_closed': (8, 1),
    'fcgrasp_height_to_bottom': (9, 1),
}

# Concatenation order of action features (ref grasp_model_input_keys :637).
E2E_GRASP_PARAM_KEYS = (
    'world_vector', 'vertical_rotation', 'close_gripper', 'open_gripper',
    'terminate_episode', 'gripper_closed', 'height_to_bottom')


class _StemConv(nn.Module):
  """conv1_1 — 6x6/2 on [B, H, W, 3], bias kept for reference parity.

  Matches the reference stem exactly (ref networks.py:449-456:
  ``slim.conv2d(..., normalizer_fn=None)`` — so unlike every later conv
  this one HAS a bias). Two TPU notes:

  * In TRAIN mode the bias is applied through ``stop_gradient``: the
    following batch norm subtracts the batch mean, so the train loss is
    invariant to the bias and its true gradient is identically zero —
    but computing that zero costs a dead 1.8 GB reduction over the
    236x236 cotangent per step. The parameter still exists
    (checkpoint/parity) and still shifts the BN running statistics
    exactly as in the reference. With ``train=False`` (frozen-stats
    fine-tuning) the invariance does NOT hold — the bias gradient flows
    normally there.
  * ``packed=True`` computes the strided conv as 3x3/1 on the
    2x2-space-to-depth grid — every output is the same dot product over
    the same 108 inputs. Default OFF: on v5e, XLA's strided conv emitter
    beats the packed form (measured 3.4 ms vs 4.6 ms at batch 256 even
    with the packing relayout excluded); the option is kept, tested, for
    generations where it wins.
  """

  packed: bool = False
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, x, train: bool = False):
    kernel = self.param('kernel',
                        nn.initializers.truncated_normal(stddev=0.01),
                        (6, 6, 3, 64), jnp.float32)
    bias = self.param('bias', nn.initializers.zeros, (64,), jnp.float32)
    b, h, w, c = x.shape
    x = jnp.asarray(x, self.dtype)
    if self.packed and h % 2 == 0 and w % 2 == 0:
      # [B, H, W, 3] -> [B, H/2, W/2, 12] with channel order (p, q, ch).
      xp = x.reshape(b, h // 2, 2, w // 2, 2, c)
      xp = xp.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
      # kernel[2a+p, 2b+q, ch, co] -> packed[a, b, (p, q, ch), co].
      kp = jnp.asarray(kernel, self.dtype).reshape(3, 2, 3, 2, c, 64)
      kp = kp.transpose(0, 2, 1, 3, 4, 5).reshape(3, 3, 4 * c, 64)
      # SAME for 6x6/2 on even H pads (2, 2); on the packed grid: (1, 1).
      out = jax.lax.conv_general_dilated(
          xp, kp, (1, 1), ((1, 1), (1, 1)),
          dimension_numbers=('NHWC', 'HWIO', 'NHWC'),
          preferred_element_type=self.dtype)
    else:
      out = jax.lax.conv_general_dilated(
          x, jnp.asarray(kernel, self.dtype), (2, 2), 'SAME',
          dimension_numbers=('NHWC', 'HWIO', 'NHWC'),
          preferred_element_type=self.dtype)
    bias = jnp.asarray(bias, self.dtype)
    return out + (jax.lax.stop_gradient(bias) if train else bias)


class _LayoutConv(nn.Module):
  """A body conv computed under NCHW/OIHW ``dimension_numbers``.

  Checkpoint-compatible with ``nn.Conv(use_bias=False)``: the parameter
  is the same ``kernel`` of shape [k, k, in, out] with the same init —
  only the CONV COMPUTATION runs through
  ``dimension_numbers=('NCHW', 'OIHW', 'NCHW')`` (operand/kernel
  transposed in-trace, result transposed back). Numerically this is the
  same contraction in a different loop order; its point is to hand XLA's
  layout assignment a different starting layout, one of the compile-
  config autotuner's sweepable variants (tuning/search_space.py
  'conv-nchw'). On the autotuner's sweep the transposes either fuse away
  (and the variant measures what the layout is worth) or they don't (and
  the candidate loses honestly).
  """

  features: int
  kernel_size: int
  stride: int = 1
  padding: str = 'SAME'
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, x):
    k = self.kernel_size
    kernel = self.param('kernel',
                        nn.initializers.truncated_normal(stddev=0.01),
                        (k, k, x.shape[-1], self.features), jnp.float32)
    x = jnp.asarray(x, self.dtype).transpose(0, 3, 1, 2)
    kernel = jnp.asarray(kernel, self.dtype).transpose(3, 2, 0, 1)
    out = jax.lax.conv_general_dilated(
        x, kernel, (self.stride, self.stride), self.padding,
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'),
        preferred_element_type=self.dtype)
    return out.transpose(0, 2, 3, 1)


class _PrePoolStatsBatchNorm(nn.Module):
  """No-scale BatchNorm whose TRAIN statistics come from the pre-pool map.

  Grasping44's first block is conv1 -> bn1(no scale) -> relu -> maxpool.
  Normalize-then-relu is a per-channel NON-DECREASING map, so it commutes
  exactly with max pooling; evaluating it AFTER the pool touches the
  79x79 map instead of the 236x236 one (8.9x less elementwise/HBM work)
  while the batch statistics are still computed over the full pre-pool
  tensor — bit-identical outputs and running stats. Parameter and
  batch_stats trees match ``nn.BatchNorm(use_scale=False)``.
  """

  momentum: float = 0.9997
  epsilon: float = 0.001
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, pre_pool, pooled, train: bool):
    features = (pre_pool.shape[-1],)
    ra_mean = self.variable('batch_stats', 'mean',
                            lambda: jnp.zeros(features, jnp.float32))
    ra_var = self.variable('batch_stats', 'var',
                           lambda: jnp.ones(features, jnp.float32))
    bias = self.param('bias', nn.initializers.zeros, features, jnp.float32)
    if train:
      xf = jnp.asarray(pre_pool, jnp.float32)
      axes = tuple(range(pre_pool.ndim - 1))
      mean = jnp.mean(xf, axis=axes)
      var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean)
      if not self.is_initializing():
        ra_mean.value = (self.momentum * ra_mean.value +
                         (1.0 - self.momentum) * mean)
        ra_var.value = (self.momentum * ra_var.value +
                        (1.0 - self.momentum) * var)
    else:
      mean, var = ra_mean.value, ra_var.value
      # Same eval-mode fusion pathology guard as Grasping44Network._bn.
      pooled = _schedule_barrier(pooled)
    # Same arithmetic flax's BatchNorm applies: operands cast to the
    # module dtype first, normalize computed in that dtype.
    x = jnp.asarray(pooled, self.dtype)
    mul = jax.lax.rsqrt(jnp.asarray(var, self.dtype) +
                        jnp.asarray(self.epsilon, self.dtype))
    return ((x - jnp.asarray(mean, self.dtype)) * mul +
            jnp.asarray(bias, self.dtype))


class Grasping44Network(nn.Module):
  """The Grasping44 Q-network (ref Grasping44FlexibleGraspParams :304)."""

  num_classes: int = 1
  num_convs: Sequence[int] = (6, 6, 3)
  hid_layers: int = 2
  batch_norm_decay: float = 0.9997
  batch_norm_epsilon: float = 0.001
  l2_regularization: float = 0.00007
  grasp_param_names: Optional[Dict[str, Tuple[int, int]]] = None
  softmax: bool = False
  dtype: jnp.dtype = jnp.float32
  # Optional exact space-to-depth rewrite of the stem conv; see
  # _StemConv for the trade-off measurements.
  space_to_depth: bool = False
  # Body-conv dimension_numbers/layout variant: 'nhwc' (stock nn.Conv)
  # or 'nchw' (_LayoutConv — same params, NCHW/OIHW compute). Sweepable
  # by the compile-config autotuner (tuning/search_space.py).
  conv_variant: str = 'nhwc'

  def _conv(self, features, kernel, stride, padding, name):
    # BN-normalized convs carry NO bias, exactly like slim.conv2d under
    # the reference's normalizer_fn=batch_norm arg_scope (ref :441-446).
    if self.conv_variant == 'nchw':
      return _LayoutConv(features=features, kernel_size=kernel,
                         stride=stride, padding=padding, dtype=self.dtype,
                         name=name)
    if self.conv_variant != 'nhwc':
      raise ValueError(
          "conv_variant must be 'nhwc' or 'nchw'; got {!r}.".format(
              self.conv_variant))
    return nn.Conv(
        features=features, kernel_size=(kernel, kernel),
        strides=(stride, stride), padding=padding, use_bias=False,
        kernel_init=nn.initializers.truncated_normal(stddev=0.01),
        dtype=self.dtype, name=name)

  def _dense(self, features, name, use_bias=True):
    # use_bias=False for the BN-normalized denses (fcgrasp2, fc0/fc1 —
    # same slim arg_scope rule); the per-block grasp-param denses and
    # the logit head keep theirs (ref :497-503, :575-581).
    return nn.Dense(
        features, use_bias=use_bias,
        kernel_init=nn.initializers.truncated_normal(stddev=0.01),
        dtype=self.dtype, name=name)

  def _bn(self, net, train, scale, name):
    if not train:
      # Keep XLA from fusing the eval-mode (running-stat) normalize INTO
      # the producing conv: on v5e that demotes the 5x5 convs from the
      # native conv emitter to a loop fusion — measured 98 ms -> 33 ms
      # for the full eval forward at batch 256 with this barrier. The
      # barrier is the identity; numerics are untouched.
      net = _schedule_barrier(net)
    return nn.BatchNorm(
        use_running_average=not train, momentum=self.batch_norm_decay,
        epsilon=self.batch_norm_epsilon, use_scale=scale,
        dtype=self.dtype, name=name)(net)

  @nn.compact
  def __call__(self, image, grasp_params, train: bool = False):
    """Args:
      image: [batch, H, W, 3] grasp image (472x472 nominal).
      grasp_params: [batch, d] or [batch, action_batch, d] (CEM megabatch).
      train: batch-norm mode.

    Returns:
      endpoints dict with 'logits', 'predictions' (sigmoid/softmax, shaped
      [batch, action_batch] in megabatch mode), 'pool2', 'final_conv'.
      Weight decay is NOT an endpoint: compute it from the params pytree
      with the module-level ``l2_regularization_loss(params, scale)``.
    """
    endpoints = {}
    tile_batch = grasp_params.ndim == 3
    action_batch_size = grasp_params.shape[1] if tile_batch else 1
    if tile_batch:
      grasp_params = grasp_params.reshape((-1, grasp_params.shape[-1]))

    net = jnp.asarray(image, self.dtype)
    net = _StemConv(packed=self.space_to_depth, dtype=self.dtype,
                    name='conv1_1')(net, train=train)
    # Pool the RAW conv output; normalize+relu (a non-decreasing
    # per-channel map — bn1 has no scale) on the 8.9x smaller pooled map
    # with statistics still taken over the full pre-pool tensor.
    pooled = max_pool(net, (3, 3), strides=(3, 3), padding='SAME')
    net = nn.relu(_PrePoolStatsBatchNorm(
        momentum=self.batch_norm_decay, epsilon=self.batch_norm_epsilon,
        dtype=self.dtype, name='bn1')(net, pooled, train))
    layer = 2
    for _ in range(self.num_convs[0]):
      net = self._conv(64, 5, 1, 'SAME', 'conv{}'.format(layer))(net)
      net = self._bn(net, train, True, 'bn{}'.format(layer))
      net = nn.relu(net)
      layer += 1
    net = max_pool(net, (3, 3), strides=(3, 3), padding='SAME')
    endpoints['pool2'] = net

    grasp_params = jnp.asarray(grasp_params, self.dtype)
    if self.grasp_param_names is None:
      blocks = [('fcgrasp', grasp_params)]
    else:
      # Sorted for deterministic parameter creation (ref :482-486).
      blocks = [
          (name, grasp_params[:, offset:offset + size])
          for name, (offset, size) in sorted(self.grasp_param_names.items())
      ]
    fcgrasp = sum(self._dense(256, name)(block) for name, block in blocks)
    fcgrasp = nn.relu(self._bn(fcgrasp, train, scale=False, name='bngrasp'))
    fcgrasp = self._dense(64, 'fcgrasp2', use_bias=False)(fcgrasp)
    fcgrasp = nn.relu(self._bn(fcgrasp, train, True, 'bngrasp2'))
    endpoints['fcgrasp'] = fcgrasp
    context = fcgrasp.reshape((-1, 1, 1, 64))

    if tile_batch:
      # Tile the IMAGE EMBEDDING (not the raw image) across the action
      # batch: [B, h, w, c] -> [B * action_batch, h, w, c] with each
      # state's block contiguous (ref contrib_seq2seq.tile_batch :526).
      net = jnp.repeat(net, action_batch_size, axis=0)
    net = net + context
    endpoints['vsum'] = net

    for _ in range(self.num_convs[1]):
      net = self._conv(64, 3, 1, 'SAME', 'conv{}'.format(layer))(net)
      net = self._bn(net, train, True, 'bn{}'.format(layer))
      net = nn.relu(net)
      layer += 1
    net = max_pool(net, (2, 2), strides=(2, 2), padding='SAME')
    for _ in range(self.num_convs[2]):
      net = self._conv(64, 3, 1, 'VALID', 'conv{}'.format(layer))(net)
      net = self._bn(net, train, True, 'bn{}'.format(layer))
      net = nn.relu(net)
      layer += 1
    endpoints['final_conv'] = net

    net = net.reshape((net.shape[0], -1))
    for l in range(self.hid_layers):
      net = self._dense(64, 'fc{}'.format(l), use_bias=False)(net)
      net = self._bn(net, train, True, 'bnfc{}'.format(l))
      net = nn.relu(net)
    name = 'logit' if self.num_classes == 1 else 'logit_{}'.format(
        self.num_classes)
    logits = nn.Dense(
        self.num_classes,
        kernel_init=nn.initializers.truncated_normal(stddev=0.01),
        dtype=jnp.float32, name=name)(jnp.asarray(net, jnp.float32))
    endpoints['logits'] = logits
    predictions = (nn.softmax(logits) if self.softmax
                   else nn.sigmoid(logits))
    if tile_batch:
      new_shape = ((-1, action_batch_size) if self.num_classes == 1 else
                   (-1, action_batch_size, self.num_classes))
      predictions = predictions.reshape(new_shape)
      logits = logits.reshape(new_shape)
      endpoints['logits'] = logits
    elif self.num_classes == 1:
      predictions = jnp.squeeze(predictions, -1)
    endpoints['predictions'] = predictions
    return endpoints


def l2_regularization_loss(params, scale: float) -> jnp.ndarray:
  """slim REGULARIZATION_LOSSES analog: ``scale * sum ||kernel||^2 / 2``.

  Applied to conv/dense kernels only (slim regularizes weights, not biases
  or batch-norm params; ref arg_scope :438).
  """
  import jax

  total = 0.0
  for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
    if str(getattr(path[-1], 'key', '')) == 'kernel':
      total = total + jnp.sum(jnp.square(jnp.asarray(leaf, jnp.float32)))
  return scale * 0.5 * total
