"""dql_grasping helpers: context merging for conv grasping models.

Parity target: /root/reference/research/dql_grasping_lib/tf_modules.py:49-101
(``tile_to_match_context``, ``add_context``) — the action-broadcast trick
QT-Opt-style critics use to merge N candidate actions with one state
embedding. (The env-run loop that lives alongside them in the reference,
run_env.py:82-239, is rl/run_env.py here; the slim ``argscope`` conv
defaults are the explicit Flax module defaults of layers/.)

TPU note: these are the building blocks of the CEM action megabatch
(networks.py docstring): net stays at batch B while the context carries
B*num_samples rows, so the expensive conv tower never re-runs per action.
"""

from __future__ import annotations

import jax.numpy as jnp


def tile_to_match_context(net: jnp.ndarray,
                          context: jnp.ndarray) -> jnp.ndarray:
  """Repeats net along a new axis=1 to match context's samples dim (ref :49).

  Args:
    net: [B, ...].
    context: [B, num_samples, C].
  Returns:
    [B, num_samples, ...] with each batch element of net tiled.
  """
  num_samples = context.shape[1]
  net_expanded = jnp.expand_dims(net, 1)
  reps = (1, num_samples) + (1,) * (net_expanded.ndim - 2)
  return jnp.tile(net_expanded, reps)


def add_context(net: jnp.ndarray, context: jnp.ndarray) -> jnp.ndarray:
  """Broadcast-adds per-action context onto conv features (ref :74).

  Args:
    net: [B, H, W, C] state features.
    context: [B * num_samples, C] action embeddings (num_samples
      contiguous rows per state).
  Returns:
    [B * num_samples, H, W, C].
  """
  batch = net.shape[0]
  h, w, d1 = net.shape[1:]
  d2 = context.shape[-1]
  if d1 != d2:
    raise ValueError('Context depth {} != net depth {}.'.format(d2, d1))
  context = context.reshape(batch, -1, d2)           # [B, S, C]
  num_samples = context.shape[1]
  net = tile_to_match_context(net, context)          # [B, S, H, W, C]
  context = context[:, :, None, None, :]             # [B, S, 1, 1, C]
  out = net + context
  return out.reshape(batch * num_samples, h, w, d1)
