"""Research workloads: concrete models + envs built on the framework."""
