"""Grasp2Vec: self-supervised object embeddings (arXiv:1811.06964)."""

from tensor2robot_tpu.research.grasp2vec import losses
from tensor2robot_tpu.research.grasp2vec import visualization
from tensor2robot_tpu.research.grasp2vec.grasp2vec_model import (
    EmbeddingNet,
    Grasp2VecModel,
    Grasp2VecPreprocessor,
    maybe_crop_images,
)

__all__ = [
    'EmbeddingNet',
    'Grasp2VecModel',
    'Grasp2VecPreprocessor',
    'losses',
    'maybe_crop_images',
    'visualization',
]
