"""Grasp2Vec model: self-supervised object embeddings (arXiv:1811.06964).

Parity target: /root/reference/research/grasp2vec/grasp2vec_model.py
(maybe_crop_images :49, Grasp2VecPreprocessor :81, Grasp2VecModel :141) and
networks.py:27-45 (ResNet-50 spatial embedding). The embedding property:
phi(pregrasp) - phi(postgrasp) ~= phi(goal).

TPU-first notes: the pregrasp/postgrasp scene batches are concatenated so
the ResNet-50 tower sees one doubled batch (one MXU-saturating pass, ref
:192-194); all image preprocessing (shared random crop, flips, uint8->f32)
runs inside the jitted step.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import resnet as resnet_lib
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.preprocessors.spec_transformation_preprocessor import (
    SpecTransformationPreprocessor,
)
from tensor2robot_tpu.research.grasp2vec import losses
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec

CropParams = Tuple[int, int, int, int, int, int]
_IMAGE_KEYS = ('pregrasp_image', 'postgrasp_image', 'goal_image')


def maybe_crop_images(key: Optional[jax.Array], images, params: CropParams,
                      mode: str):
  """Crops every batch in ``images`` at one shared offset (ref :49-77).

  TRAIN samples the offset uniformly from the configured window; other
  modes use the window center. Offsets are traced scalars — the crop is a
  dynamic_slice with static target size, XLA-friendly.
  """
  (min_oh, max_oh, target_h, min_ow, max_ow, target_w) = params
  if mode == ModeKeys.TRAIN:
    if key is None:
      raise ValueError('TRAIN-mode cropping requires an rng key.')
    kh, kw = jax.random.split(key)
    offset_h = jax.random.randint(kh, (), min_oh, max(max_oh, min_oh + 1))
    offset_w = jax.random.randint(kw, (), min_ow, max(max_ow, min_ow + 1))
  else:
    offset_h = jnp.asarray((min_oh + max_oh) // 2)
    offset_w = jnp.asarray((min_ow + max_ow) // 2)

  def _crop(batch):
    return jax.lax.dynamic_slice(
        batch, (0, offset_h, offset_w, 0),
        (batch.shape[0], target_h, target_w, batch.shape[3]))

  return [_crop(img) for img in images], offset_h, offset_w


class Grasp2VecPreprocessor(SpecTransformationPreprocessor):
  """512x640 uint8 jpegs -> shared-crop, flipped float32 (ref :81-137)."""

  def __init__(self,
               model_feature_specification_fn=None,
               model_label_specification_fn=None,
               scene_crop: CropParams = (0, 40, 472, 0, 168, 472),
               goal_crop: CropParams = (0, 40, 472, 0, 168, 472),
               src_img_shape: Tuple[int, int, int] = (512, 640, 3)):
    super().__init__(model_feature_specification_fn,
                     model_label_specification_fn)
    self._scene_crop = tuple(scene_crop)
    self._goal_crop = tuple(goal_crop)
    self._src_img_shape = tuple(src_img_shape)

  def update_spec_transform(self, key: str, spec: TensorSpec,
                            mode: str) -> TensorSpec:
    del mode
    if key in _IMAGE_KEYS:
      return TensorSpec.from_spec(spec, shape=self._src_img_shape,
                                  dtype=np.uint8, data_format='jpeg')
    return spec

  def _preprocess_fn(self, features, labels, mode: str, rng=None):
    rngs = (jax.random.split(jnp.asarray(rng), 4) if rng is not None
            else [None] * 4)
    scene_images, _, _ = maybe_crop_images(
        rngs[0], [jnp.asarray(features['pregrasp_image']),
                  jnp.asarray(features['postgrasp_image'])],
        self._scene_crop, mode)
    goal_images, _, _ = maybe_crop_images(
        rngs[1], [jnp.asarray(features['goal_image'])], self._goal_crop,
        mode)
    images = dict(zip(_IMAGE_KEYS,
                      [scene_images[0], scene_images[1], goal_images[0]]))
    for idx, (name, image) in enumerate(images.items()):
      image = jnp.asarray(image, jnp.float32) / 255.0
      if mode == ModeKeys.TRAIN:
        # Per-image random flips (ref :133-135), one coin per example.
        flip_rng = jax.random.fold_in(rngs[2], idx)
        klr, kud = jax.random.split(flip_rng)
        batch = image.shape[0]
        flip_lr = jax.random.bernoulli(klr, shape=(batch, 1, 1, 1))
        flip_ud = jax.random.bernoulli(kud, shape=(batch, 1, 1, 1))
        image = jnp.where(flip_lr, image[:, :, ::-1, :], image)
        image = jnp.where(flip_ud, image[:, ::-1, :, :], image)
      features[name] = image
    return features, labels


class EmbeddingNet(nn.Module):
  """ResNet-50 spatial embedding tower (ref networks.py:27-45).

  Returns (mean-pooled embedding [B, D], relu spatial map [B, h, w, D]).
  """

  resnet_size: int = 50
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, image, train: bool = False):
    _, endpoints = resnet_lib.ResNet(
        resnet_size=self.resnet_size, dtype=self.dtype, name='resnet')(
            image, train=train, include_head=False)
    spatial = nn.relu(endpoints['pre_final_pool'])
    summed = jnp.mean(spatial, axis=(1, 2))
    return (jnp.asarray(summed, jnp.float32),
            jnp.asarray(spatial, jnp.float32))


class _Grasp2VecNet(nn.Module):
  """Scene + goal towers over the feature struct (ref :185-208)."""

  resnet_size: int = 50
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, features, mode: str = ModeKeys.TRAIN,
               train: bool = False):
    # One doubled batch through the scene tower (ref :192-194).
    scene_images = jnp.concatenate(
        [jnp.asarray(features['pregrasp_image'], self.dtype),
         jnp.asarray(features['postgrasp_image'], self.dtype)], axis=0)
    scene_tower = EmbeddingNet(resnet_size=self.resnet_size,
                               dtype=self.dtype, name='scene')
    v, s = scene_tower(scene_images, train=train)
    pre_v, post_v = jnp.split(v, 2, axis=0)
    pre_s, post_s = jnp.split(s, 2, axis=0)
    goal_v, goal_s = EmbeddingNet(resnet_size=self.resnet_size,
                                  dtype=self.dtype, name='goal')(
        jnp.asarray(features['goal_image'], self.dtype), train=train)
    return SpecStruct(
        pre_vector=pre_v, post_vector=post_v,
        pre_spatial=pre_s, post_spatial=post_s,
        goal_vector=goal_v, goal_spatial=goal_s)


class Grasp2VecModel(AbstractT2RModel):
  """Grasp2Vec embedding model (ref :141-245)."""

  def __init__(self,
               scene_size: Tuple[int, int] = (472, 472),
               goal_size: Tuple[int, int] = (472, 472),
               embedding_loss_fn: Callable = losses.n_pairs_loss,
               resnet_size: int = 50,
               preprocessor_cls=Grasp2VecPreprocessor,
               **kwargs):
    """Args mirror ref :144-160; embedding_loss_fn is n_pairs_loss or
    the triplet variant (losses.py)."""
    kwargs.setdefault('device_type', 'cpu')
    super().__init__(preprocessor_cls=preprocessor_cls, **kwargs)
    self._scene_size = tuple(scene_size)
    self._goal_size = tuple(goal_size)
    self._embedding_loss_fn = embedding_loss_fn
    self._resnet_size = resnet_size

  def get_feature_specification(self, mode: str) -> SpecStruct:
    """ref :162-174 (on-disk names image/postgrasp_image/present_image)."""
    del mode
    return SpecStruct(
        pregrasp_image=TensorSpec(self._scene_size + (3,), np.float32,
                                  name='image', data_format='jpeg'),
        postgrasp_image=TensorSpec(self._scene_size + (3,), np.float32,
                                   name='postgrasp_image',
                                   data_format='jpeg'),
        goal_image=TensorSpec(self._goal_size + (3,), np.float32,
                              name='present_image', data_format='jpeg'))

  def get_label_specification(self, mode: str) -> SpecStruct:
    """Grasp2Vec is self-supervised: no labels (ref :176-179)."""
    del mode
    return SpecStruct()

  def create_network(self) -> nn.Module:
    return _Grasp2VecNet(resnet_size=self._resnet_size,
                         dtype=jnp.dtype(self.compute_dtype))

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    """ref :210-222."""
    embed_loss = self._embedding_loss_fn(
        inference_outputs['pre_vector'],
        inference_outputs['goal_vector'],
        inference_outputs['post_vector'])
    if isinstance(embed_loss, tuple):  # triplet_loss returns (loss, ...)
      embed_loss = embed_loss[0]
    return embed_loss, SpecStruct(embed_loss=embed_loss)

  def model_eval_fn(self, variables, features, labels, inference_outputs,
                    mode: str) -> SpecStruct:
    loss, train_outputs = self.model_train_fn(
        variables, features, labels, inference_outputs, mode)
    metrics = SpecStruct(loss=loss)
    for key in train_outputs:
      metrics[key] = train_outputs[key]
    return metrics

  def add_summaries(self, features, labels, inference_outputs, mode: str):
    """Heatmaps, keypoints, and distance histograms (ref :224-245)."""
    del labels, mode
    from tensor2robot_tpu.research.grasp2vec import visualization

    raw = visualization.grasp2vec_summaries(features, inference_outputs)
    images = {k: v for k, v in raw.items() if not k.startswith('hist/')}
    histograms = {k[len('hist/'):]: v for k, v in raw.items()
                  if k.startswith('hist/')}
    return {'images': images, 'histograms': histograms}
