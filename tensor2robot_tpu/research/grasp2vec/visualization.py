"""Grasp2Vec heatmap / keypoint visualization.

Parity target: /root/reference/research/grasp2vec/visualization.py:39-249.
The reference emits tf.summary images/histograms as a graph side effect;
here each helper is a pure function returning arrays, and
``grasp2vec_summaries`` packages them as a {name: array} dict the metrics
writer (trainer/metrics.py) logs as images/histograms.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def compute_heatmaps(feature_query, feature_map) -> jnp.ndarray:
  """Dot product of a query embedding across a spatial map (ref :81-102).

  Args:
    feature_query: [B, D] goal embeddings.
    feature_map: [B, h, w, D] scene spatial embeddings.
  Returns:
    [B, h, w, 1] heatmaps.
  """
  batch, dim = feature_query.shape
  query = jnp.asarray(feature_query, jnp.float32).reshape(batch, 1, 1, dim)
  return jnp.sum(jnp.asarray(feature_map, jnp.float32) * query, axis=3,
                 keepdims=True)


def softmax_heatmaps(heatmaps: jnp.ndarray) -> jnp.ndarray:
  """Spatially softmaxed heatmaps, same shape (ref :96-100)."""
  batch = heatmaps.shape[0]
  flat = jax.nn.softmax(heatmaps.reshape(batch, -1), axis=1)
  return flat.reshape(heatmaps.shape)


def heatmap_spatial_soft_argmax(heatmaps: jnp.ndarray,
                                temperature: float = 0.1) -> jnp.ndarray:
  """Expected (x, y) of the softmaxed heatmap in [-1, 1] (ref :105-115)."""
  batch, height, width, _ = heatmaps.shape
  probs = jax.nn.softmax(
      heatmaps.reshape(batch, -1) / temperature, axis=1).reshape(
          batch, height, width)
  ys = jnp.linspace(-1.0, 1.0, height)
  xs = jnp.linspace(-1.0, 1.0, width)
  expected_y = jnp.sum(probs * ys[None, :, None], axis=(1, 2))
  expected_x = jnp.sum(probs * xs[None, None, :], axis=(1, 2))
  return jnp.stack([expected_x, expected_y], axis=-1)[:, None, :]


def np_render_keypoints(image: np.ndarray, locations: np.ndarray,
                        num_images: int = 3, dot_radius: int = 3
                        ) -> np.ndarray:
  """Rasterizes keypoint locations onto images (ref :118-171).

  Args:
    image: [N, H, W, 3] float images in [0, 1].
    locations: [N, C, 2] (x, y) locations in [-1, 1].
  Returns:
    [num_images, H, W, 3] annotated copies.
  """
  image = np.asarray(image, np.float32)
  locations = np.asarray(locations)
  num_images = min(num_images, image.shape[0])
  out = image[:num_images].copy()
  height, width = image.shape[1:3]
  for n in range(num_images):
    for c in range(locations.shape[1]):
      x, y = locations[n, c]
      col = int((x + 1.0) / 2.0 * (width - 1))
      row = int((y + 1.0) / 2.0 * (height - 1))
      r0, r1 = max(0, row - dot_radius), min(height, row + dot_radius + 1)
      c0, c1 = max(0, col - dot_radius), min(width, col + dot_radius + 1)
      out[n, r0:r1, c0:c1] = np.asarray([1.0, 0.0, 0.0])
  return out


def distance_histograms(pregrasp, goal, postgrasp) -> Dict[str, np.ndarray]:
  """The evaluation histograms of ref plot_distances (:63-79), as arrays."""
  pregrasp = np.asarray(pregrasp, np.float32)
  goal = np.asarray(goal, np.float32)
  postgrasp = np.asarray(postgrasp, np.float32)
  goal_normalized = goal / (1e-7 + np.linalg.norm(goal, axis=1,
                                                  keepdims=True))
  return {
      'correct_distances': np.linalg.norm(pregrasp - (goal + postgrasp),
                                          axis=1),
      'incorrect_distances': np.linalg.norm(pregrasp - pregrasp[::-1],
                                            axis=1),
      'goal_distances': np.linalg.norm(goal - goal[::-1], axis=1),
      'pregrasp_sizes': np.linalg.norm(pregrasp, axis=1),
      'postgrasp_sizes': np.linalg.norm(postgrasp, axis=1),
      'goal_sizes': np.linalg.norm(goal, axis=1),
      'goal_cosine_similarity': np.sum(
          goal_normalized[:-1] * goal_normalized[1:], axis=1),
  }


def grasp2vec_summaries(features, inference_outputs
                        ) -> Dict[str, np.ndarray]:
  """All add_summaries artifacts as a {name: array} dict (ref :224-246).

  Images come back as [N, H, W, C] float arrays; 1-D entries are histogram
  samples. Feed to MetricsWriter.write_images/write_histograms.
  """
  out: Dict[str, np.ndarray] = {}
  for key in ('pregrasp', 'postgrasp', 'goal'):
    name = key + '_image'
    if name in features:
      out['image/' + key] = np.asarray(features[name])[:3]
  heatmaps = compute_heatmaps(inference_outputs['goal_vector'],
                              inference_outputs['pre_spatial'])
  out['goal_pregrasp_map'] = np.asarray(heatmaps)[:3]
  out['goal_pregrasp_map_softmax'] = np.asarray(
      softmax_heatmaps(heatmaps))[:3]
  locations = heatmap_spatial_soft_argmax(heatmaps)
  if 'pregrasp_image' in features:
    out['keypoints'] = np_render_keypoints(
        np.asarray(features['pregrasp_image']), np.asarray(locations))
  for name, values in distance_histograms(
      inference_outputs['pre_vector'], inference_outputs['goal_vector'],
      inference_outputs['post_vector']).items():
    out['hist/' + name] = values
  return out
