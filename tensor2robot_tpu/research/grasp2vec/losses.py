"""Grasp2Vec embedding losses (arXiv:1811.06964).

Parity target: /root/reference/research/grasp2vec/losses.py:34-308. The
tf-slim metric-learning primitives the reference calls are implemented
natively:

  * ``npairs_loss``      — softmax cross entropy over the similarity matrix
                           with row-normalized label-equality targets plus
                           the 0.25 * reg_lambda * mean||e||^2 regularizer
                           (slim metric_learning.npairs_loss semantics).
  * ``triplet_semihard_loss`` — semi-hard negative mining over the pairwise
                           distance matrix (slim triplet_semihard_loss).

Masked variants replace tf.dynamic_partition/tf.cond with arithmetic
masking — identical values, no data-dependent control flow, so the losses
jit cleanly on TPU.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _masked_mean(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
  """Mean over mask==1 entries; exact 0.0 when the mask is empty (ref tf.cond)."""
  mask = jnp.asarray(mask, jnp.float32).reshape(values.shape)
  total = jnp.sum(mask)
  return jnp.where(total > 0, jnp.sum(values * mask) / jnp.maximum(total, 1.0),
                   0.0)


def l2_arithmetic_loss(pregrasp_embedding, goal_embedding,
                       postgrasp_embedding, mask) -> jnp.ndarray:
  """mean ||pre - goal - post||^2 over masked rows (ref :34-57)."""
  raw = (jnp.asarray(pregrasp_embedding, jnp.float32) -
         jnp.asarray(goal_embedding, jnp.float32) -
         jnp.asarray(postgrasp_embedding, jnp.float32))
  distances = jnp.sum(raw ** 2, axis=1)
  return _masked_mean(distances, mask)


def cosine_arithmetic_loss(pregrasp_embedding, goal_embedding,
                           postgrasp_embedding, mask) -> jnp.ndarray:
  """Masked mean cosine distance of (pre - post) vs goal (ref :85-112)."""
  pair_a = _l2_normalize(
      jnp.asarray(pregrasp_embedding, jnp.float32) -
      jnp.asarray(postgrasp_embedding, jnp.float32))
  pair_b = _l2_normalize(jnp.asarray(goal_embedding, jnp.float32))
  distances = 1.0 - jnp.sum(pair_a * pair_b, axis=1)
  return _masked_mean(distances, mask)


def send_to_zero_loss(tensor, mask) -> jnp.ndarray:
  """Masked mean L2 norm (ref :143-161)."""
  distances = jnp.linalg.norm(jnp.asarray(tensor, jnp.float32), axis=1)
  return _masked_mean(distances, mask)


def _l2_normalize(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
  return x / jnp.maximum(jnp.linalg.norm(x, axis=axis, keepdims=True), 1e-12)


def npairs_loss(labels: jnp.ndarray, embeddings_anchor: jnp.ndarray,
                embeddings_positive: jnp.ndarray,
                reg_lambda: float = 0.002) -> jnp.ndarray:
  """slim metric_learning.npairs_loss semantics.

  xent(similarity_matrix, row-normalized label equality) +
  reg_lambda * 0.25 * (mean||a||^2 + mean||b||^2).
  """
  anchor = jnp.asarray(embeddings_anchor, jnp.float32)
  positive = jnp.asarray(embeddings_positive, jnp.float32)
  reg_anchor = jnp.mean(jnp.sum(anchor ** 2, axis=1))
  reg_positive = jnp.mean(jnp.sum(positive ** 2, axis=1))
  l2loss = 0.25 * reg_lambda * (reg_anchor + reg_positive)
  similarity = anchor @ positive.T
  labels = jnp.asarray(labels)
  labels_equal = (labels[:, None] == labels[None, :]).astype(jnp.float32)
  labels_remapped = labels_equal / jnp.sum(labels_equal, axis=1,
                                           keepdims=True)
  xent = -jnp.sum(labels_remapped * jax.nn.log_softmax(similarity, axis=1),
                  axis=1)
  return jnp.mean(xent) + l2loss


def npairs_loss_multilabel(multilabels: jnp.ndarray,
                           embeddings_anchor: jnp.ndarray,
                           embeddings_positive: jnp.ndarray,
                           reg_lambda: float = 0.002) -> jnp.ndarray:
  """slim npairs_loss_multilabel with DENSE multilabel one-hots.

  ``multilabels``: [batch, num_classes] {0,1}; label similarity is the
  Jaccard-style normalized intersection slim computes from sparse labels.
  """
  anchor = jnp.asarray(embeddings_anchor, jnp.float32)
  positive = jnp.asarray(embeddings_positive, jnp.float32)
  reg_anchor = jnp.mean(jnp.sum(anchor ** 2, axis=1))
  reg_positive = jnp.mean(jnp.sum(positive ** 2, axis=1))
  l2loss = 0.25 * reg_lambda * (reg_anchor + reg_positive)
  multilabels = jnp.asarray(multilabels, jnp.float32)
  intersection = multilabels @ multilabels.T
  labels_remapped = intersection / jnp.maximum(
      jnp.sum(intersection, axis=1, keepdims=True), 1e-12)
  similarity = anchor @ positive.T
  xent = -jnp.sum(labels_remapped * jax.nn.log_softmax(similarity, axis=1),
                  axis=1)
  return jnp.mean(xent) + l2loss


def n_pairs_loss(pregrasp_embedding, goal_embedding, postgrasp_embedding,
                 non_negativity_constraint: bool = False) -> jnp.ndarray:
  """Bidirectional npairs on (pre - post, goal) (ref NPairsLoss :164-190)."""
  pair_a = (jnp.asarray(pregrasp_embedding, jnp.float32) -
            jnp.asarray(postgrasp_embedding, jnp.float32))
  if non_negativity_constraint:
    pair_a = jax.nn.relu(pair_a)
  pair_b = jnp.asarray(goal_embedding, jnp.float32)
  labels = jnp.arange(pair_a.shape[0])
  return (npairs_loss(labels, pair_a, pair_b) +
          npairs_loss(labels, pair_b, pair_a))


def n_pairs_loss_multilabel(pregrasp_embedding, goal_embedding,
                            postgrasp_embedding, grasp_success
                            ) -> jnp.ndarray:
  """ref NPairsLossMultilabel :193-224: failed grasps share label 0."""
  pair_a = (jnp.asarray(pregrasp_embedding, jnp.float32) -
            jnp.asarray(postgrasp_embedding, jnp.float32))
  pair_b = jnp.asarray(goal_embedding, jnp.float32)
  batch = pair_a.shape[0]
  grasp_success = jnp.asarray(grasp_success).reshape(batch).astype(jnp.int32)
  range_tensor = jnp.arange(batch, dtype=jnp.int32) * grasp_success
  multilabels = jax.nn.one_hot(range_tensor, batch + 1)
  return (npairs_loss_multilabel(multilabels, pair_a, pair_b) +
          npairs_loss_multilabel(multilabels, pair_b, pair_a))


def _pairwise_squared_distances(a: jnp.ndarray) -> jnp.ndarray:
  sq = jnp.sum(a ** 2, axis=1)
  d = sq[:, None] - 2.0 * (a @ a.T) + sq[None, :]
  return jnp.maximum(d, 0.0)


def _masked_minimum(data: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
  """Row-wise min over mask==1 entries (slim masked_minimum)."""
  axis_max = jnp.max(data, axis=1, keepdims=True)
  return jnp.min((data - axis_max) * mask, axis=1, keepdims=True) + axis_max


def _masked_maximum(data: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
  """Row-wise max over mask==1 entries (slim masked_maximum)."""
  axis_min = jnp.min(data, axis=1, keepdims=True)
  return jnp.max((data - axis_min) * mask, axis=1, keepdims=True) + axis_min


def triplet_semihard_loss(labels: jnp.ndarray, embeddings: jnp.ndarray,
                          margin: float = 1.0) -> jnp.ndarray:
  """slim metric_learning.triplet_semihard_loss, faithfully.

  For each positive pair (i, j): the negative is the closest one farther
  than d(i, j) if such exists (semi-hard), else the farthest negative.
  Loss = sum over positive pairs of relu(margin + d_ij - d_in) / count.
  """
  labels = jnp.asarray(labels).reshape(-1)
  embeddings = jnp.asarray(embeddings, jnp.float32)
  batch = embeddings.shape[0]
  pdist = _pairwise_squared_distances(embeddings)
  adjacency = (labels[:, None] == labels[None, :])
  adjacency_not = (~adjacency).astype(jnp.float32)

  # Row r = j*batch + i of the tiled matrix holds d(i, k) compared against
  # d(i, j) — negatives of anchor i farther than its positive j.
  pdist_tile = jnp.tile(pdist, (batch, 1))
  mask = jnp.tile(adjacency_not, (batch, 1)) * (
      pdist_tile > pdist.T.reshape(-1, 1)).astype(jnp.float32)
  mask_final = (jnp.sum(mask, axis=1, keepdims=True) > 0.0).reshape(
      batch, batch).T

  negatives_outside = _masked_minimum(pdist_tile, mask).reshape(
      batch, batch).T
  negatives_inside = jnp.tile(_masked_maximum(pdist, adjacency_not),
                              (1, batch))
  semi_hard_negatives = jnp.where(mask_final, negatives_outside,
                                  negatives_inside)
  loss_mat = margin + pdist - semi_hard_negatives

  mask_positives = adjacency.astype(jnp.float32) - jnp.eye(batch)
  num_positives = jnp.maximum(jnp.sum(mask_positives), 1e-16)
  return jnp.sum(jnp.maximum(loss_mat * mask_positives, 0.0)) / num_positives


def triplet_loss(pregrasp_embedding, goal_embedding, postgrasp_embedding,
                 margin: float = 3.0
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
  """Semi-hard triplet on normalized (pre-post, goal) pairs (ref :61-82)."""
  pair_a = _l2_normalize(
      jnp.asarray(pregrasp_embedding, jnp.float32) -
      jnp.asarray(postgrasp_embedding, jnp.float32), axis=1)
  pair_b = _l2_normalize(jnp.asarray(goal_embedding, jnp.float32), axis=1)
  labels = jnp.tile(jnp.arange(pair_a.shape[0]), (2,))
  pairs = jnp.concatenate([pair_a, pair_b], axis=0)
  loss = triplet_semihard_loss(labels, pairs, margin=margin)
  return loss, pairs, labels


def match_norms_loss(anchor_tensors, paired_tensors) -> jnp.ndarray:
  """Pushes paired norms toward (stop-gradient) anchor norms (ref :227-243)."""
  anchor_norms = jax.lax.stop_gradient(
      jnp.linalg.norm(jnp.asarray(anchor_tensors, jnp.float32), axis=1))
  paired_norms = jnp.linalg.norm(
      jnp.asarray(paired_tensors, jnp.float32), axis=1)
  return jnp.mean(0.5 * (anchor_norms - paired_norms) ** 2)


def keypoint_accuracy(keypoints, labels) -> Tuple[jnp.ndarray, jnp.ndarray]:
  """Quadrant accuracy of spatial-softmax keypoints (ref :115-140)."""
  keypoints = jnp.asarray(keypoints, jnp.float32).reshape(-1, 2)
  quadrant_centers = jnp.asarray(
      [[0.5, -0.5], [-0.5, -0.5], [0.5, 0.5], [-0.5, 0.5]], jnp.float32)
  logits = keypoints @ quadrant_centers.T
  labels = jnp.asarray(labels).reshape(-1)
  correct = (labels == jnp.argmax(logits, axis=1)).astype(jnp.float32)
  labels_onehot = jax.nn.one_hot(labels, 4)
  loss = jnp.mean(
      jnp.maximum(logits, 0) - logits * labels_onehot +
      jnp.log1p(jnp.exp(-jnp.abs(logits))))
  return jnp.mean(correct), loss


def get_softmax_response(goal_embedding, scene_spatial
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
  """Max heatmap response of a goal embedding in a scene (ref :246-271)."""
  batch, dim = goal_embedding.shape
  query = jnp.asarray(goal_embedding, jnp.float32).reshape(batch, 1, 1, dim)
  heatmap = jnp.sum(jnp.asarray(scene_spatial, jnp.float32) * query, axis=3)
  flat = heatmap.reshape(batch, -1)
  max_heat = jnp.max(flat, axis=1)
  max_soft = jnp.max(jax.nn.softmax(flat, axis=1), axis=1)
  return max_heat, max_soft


def ty_loss(pregrasp_spatial, postgrasp_spatial, goal_embedding
            ) -> jnp.ndarray:
  """Likelihood-ratio detection loss (ref TYloss :274-308)."""
  pregrasp_spatial = _l2_normalize(
      jnp.asarray(pregrasp_spatial, jnp.float32))
  postgrasp_spatial = _l2_normalize(
      jnp.asarray(postgrasp_spatial, jnp.float32))
  goal = _l2_normalize(jnp.asarray(goal_embedding, jnp.float32))
  goal = goal[:, None, None, :]
  pre_max = jnp.max(jnp.sum(pregrasp_spatial * goal, axis=-1), axis=(1, 2))
  post_max = jnp.max(jnp.sum(postgrasp_spatial * goal, axis=-1), axis=(1, 2))
  return jnp.mean(post_max - pre_max)
