"""tensor2robot_tpu: a TPU-native robot-learning framework (JAX/XLA/pjit/Pallas).

A ground-up redesign with the capabilities of Google's Tensor2Robot: a
declarative tensor-spec system that auto-generates input pipelines, runtime
validation, and serving signatures; a model abstraction training data-parallel
over TPU meshes in native bfloat16; async checkpointing and spec-carrying
exports; polling predictors and robot-control policies; MAML meta-learning;
and a vision layer library with Pallas TPU kernels.
"""

__version__ = '0.1.0'
