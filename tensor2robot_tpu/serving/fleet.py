"""ServingFleet: an autoscaled, telemetry-routed PolicyServer replica set.

The orchestration half of ISSUE 14 (the router is `serving/router.py`):
owns the replica lifecycle — spinning replicas up from a factory
(normally the persisted ``CompiledArtifact``, so replicas 2..N compile
NOTHING — the PR 12 zero-compile scale-out), draining them down through
the existing close-then-terminate batcher contract (zero drops), walking
rolling hot-swap waves one replica at a time (both weight versions serve
during the wave; the per-replica drain-free swap guarantees it locally),
and scaling the set against the demand curve.

Telemetry layout (the PR 8 indexed-filename convention, per SATELLITE):
the fleet's model_dir is fleet-shaped — the ROUTER owns stream 0
(``telemetry.0.jsonl``: ``t2r.serving_fleet.v1`` windows, scale/eject/
swap events, the fleet heartbeat) and replica *i* owns stream *i*
(its PolicyServer's ``serving`` SLO windows + heartbeat). Replica ids
are 1-based for exactly this reason: ``discover_hosts`` picks the
lowest-index stream as the primary, which is the router's — so doctor /
``t2r_telemetry`` judge the FLEET record in a fleet-shaped serving dir
and the per-replica streams federate underneath it.

``t2r.serving_fleet.v1`` window record (kind=``serving_fleet``):
per-replica table (windowed p99, queue depth, routing weight, ejected
flag, params version), fleet aggregate actions/sec + end-to-end
p50/p95/p99 vs the SLO, ejection/scale/shed totals, and the set of
params versions currently serving (a rolling wave shows two).

Jax-free at import, like the rest of serving/ — the factory owns
whatever device code a replica needs.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from tensor2robot_tpu.observability import TelemetryLogger, get_registry
from tensor2robot_tpu.reliability.logutil import log_warning
from tensor2robot_tpu.serving.router import (
    FleetRouter,
    ReplicaHandle,
    RouterConfig,
    RoutedResult,
)

__all__ = ['ServingFleet', 'ServingFleetConfig', 'replica_host_meta',
           'router_host_meta', 'SERVING_FLEET_RECORD_KIND',
           'SERVING_FLEET_SCHEMA', 'SERVING_FLEET_BENCH_KEYS',
           'FLEET_SCALE_UPS_COUNTER', 'FLEET_SCALE_DOWNS_COUNTER']

SERVING_FLEET_RECORD_KIND = 'serving_fleet'
SERVING_FLEET_SCHEMA = 't2r.serving_fleet.v1'

FLEET_SCALE_UPS_COUNTER = 'serving_fleet/scale_ups'
FLEET_SCALE_DOWNS_COUNTER = 'serving_fleet/scale_downs'

# The serving-fleet bench axis, schema-locked by bin/check_serving_slo
# (same discipline as E2E_WIRE/REPLAY/RL_LOOP/COLDSTART keys): the
# throughput-at-SLO scaling curve vs replica count, the zero-compile
# contracts (request time AND artifact-warm scale-up), the scale-up
# readiness latency, and the mid-load rolling swap outcome.
SERVING_FLEET_BENCH_KEYS = (
    'serving_fleet_actions_per_sec_r1',
    'serving_fleet_actions_per_sec_r2',
    'serving_fleet_actions_per_sec_r4',
    'serving_fleet_p99_ms_r1',
    'serving_fleet_p99_ms_r2',
    'serving_fleet_p99_ms_r4',
    'serving_fleet_scaling_monotonic',
    'serving_fleet_request_time_compiles',
    'serving_fleet_scaleup_compiles',
    'fleet_scaleup_time_to_ready_s',
    'serving_fleet_swap_failed',
    'serving_fleet_swap_versions_served',
)


def router_host_meta(max_replicas: int) -> Dict[str, object]:
  """The router's stream-0 identity in a fleet-shaped serving dir."""
  return {'process_index': 0, 'process_count': int(max_replicas) + 1}


def replica_host_meta(replica_id: int,
                      max_replicas: int) -> Dict[str, object]:
  """Replica *i*'s indexed-stream identity (``telemetry.<i>.jsonl``).

  Replica ids are 1-based: stream 0 is the router's, so the primary
  stream ``discover_hosts`` picks for a fleet dir is the fleet view.
  """
  if int(replica_id) < 1:
    raise ValueError('replica ids are 1-based (stream 0 is the '
                     'router\'s); got {}.'.format(replica_id))
  return {'process_index': int(replica_id),
          'process_count': int(max_replicas) + 1}


@dataclasses.dataclass
class ServingFleetConfig:
  """Knobs for one ServingFleet.

  Attributes:
    min_replicas / max_replicas: the autoscaler's bounds (and the
      ``process_count`` stamped into the per-replica streams).
    autoscale: run the scale-up/-down policy in the report loop.
    scale_up_at / scale_down_at: fleet utilization (router outstanding
      over fleet queue capacity) thresholds; crossing one for
      ``scale_windows`` CONSECUTIVE report windows triggers a scale
      event — one bursty window moves nothing.
    scale_windows: the consecutive-window hysteresis above.
    report_interval_s: cadence of ``t2r.serving_fleet.v1`` records (and
      autoscale decisions).
    health_interval_s / stale_after_s / max_fleet_pending: forwarded to
      the router (see :class:`~...router.RouterConfig`).
    slo_ms: the fleet-level end-to-end latency objective; per-replica
      SLOs live in each replica's own ServingConfig.
    drain_timeout_s: scale-down / close drain budget per replica.
  """

  min_replicas: int = 1
  max_replicas: int = 4
  autoscale: bool = False
  scale_up_at: float = 0.75
  scale_down_at: float = 0.1
  scale_windows: int = 2
  report_interval_s: float = 10.0
  health_interval_s: float = 1.0
  stale_after_s: float = 30.0
  max_fleet_pending: Optional[int] = None
  slo_ms: float = 33.0
  drain_timeout_s: float = 30.0


class ServingFleet:
  """N PolicyServer replicas behind one router, scaled and swapped.

  Args:
    replica_factory: ``(replica_id, telemetry) -> ReplicaHandle`` —
      builds ONE ready-to-serve replica. ``telemetry`` is the replica's
      indexed-stream TelemetryLogger under the fleet model_dir (None
      when the fleet runs without one); pass it to the PolicyServer so
      the replica reports into its own stream. The production factory
      deserializes the persisted serving artifact, so every replica
      after the first costs zero XLA compiles (asserted in the bench).
    config: :class:`ServingFleetConfig`.
    model_dir: the fleet-shaped serving dir (see module docstring);
      None = registry metrics only.
    initial_replicas: replicas spun up by :meth:`start`.
  """

  def __init__(self,
               replica_factory: Callable[[int, Optional[TelemetryLogger]],
                                         ReplicaHandle],
               config: Optional[ServingFleetConfig] = None,
               model_dir: Optional[str] = None,
               initial_replicas: int = 1,
               registry=None,
               clock: Callable[[], float] = time.monotonic):
    self.config = config or ServingFleetConfig()
    if not (1 <= self.config.min_replicas <= self.config.max_replicas):
      raise ValueError(
          'need 1 <= min_replicas <= max_replicas; got {}..{}.'.format(
              self.config.min_replicas, self.config.max_replicas))
    self._factory = replica_factory
    self._clock = clock
    self._registry = registry or get_registry()
    self._initial_replicas = int(initial_replicas)
    self.model_dir = model_dir
    self._telemetry: Optional[TelemetryLogger] = None
    if model_dir is not None:
      self._telemetry = TelemetryLogger(
          model_dir, host_meta=router_host_meta(self.config.max_replicas))
    self._replica_telemetry: Dict[int, TelemetryLogger] = {}
    self.router = FleetRouter(
        [], config=RouterConfig(
            health_interval_s=self.config.health_interval_s,
            stale_after_s=self.config.stale_after_s,
            max_fleet_pending=self.config.max_fleet_pending),
        on_event=self._on_router_event, registry=self._registry,
        clock=clock)
    self._scale_ups = self._registry.counter(FLEET_SCALE_UPS_COUNTER)
    self._scale_downs = self._registry.counter(FLEET_SCALE_DOWNS_COUNTER)

    self._lock = threading.Lock()  # replica-set mutations (scale, swap)
    self._next_replica_id = 1
    # The newest rolling-swap payload: a replica that was EJECTED while
    # a wave walked the fleet missed it, and on re-arm it must not
    # silently rejoin rotation serving the old version.
    self._last_swap: Optional[Tuple[Any, int]] = None
    self._ejections_window = 0
    self._scale_events_window = 0
    self._util_high_streak = 0
    self._util_low_streak = 0
    self._window_started = self._clock()
    self.last_record: Optional[Dict[str, object]] = None
    self.last_scaleup_seconds: Optional[float] = None

    self._stop = threading.Event()
    self._reporter: Optional[threading.Thread] = None
    self._started = False
    self._closed = False

  # -- lifecycle --------------------------------------------------------------

  def start(self) -> 'ServingFleet':
    if self._started:
      raise RuntimeError('ServingFleet already started.')
    self._started = True
    try:
      if self._telemetry is not None:
        self._telemetry.log(
            'serving_fleet_start',
            config={'min_replicas': self.config.min_replicas,
                    'max_replicas': self.config.max_replicas,
                    'autoscale': self.config.autoscale,
                    'slo_ms': self.config.slo_ms,
                    'report_interval_s': self.config.report_interval_s},
            initial_replicas=self._initial_replicas)
      for _ in range(self._initial_replicas):
        self._spawn_replica()
      self.router.start()
      self._window_started = self._clock()
      self._reporter = threading.Thread(target=self._report_loop,
                                        name='t2r-serving-fleet',
                                        daemon=True)
      self._reporter.start()
    except Exception:
      # A spawn that fails mid-boot (replica 2 of 3) must not strand
      # the replicas that DID start, their streams, or the router
      # stream — clean up, then surface the original failure.
      self.close()
      raise
    return self

  def __enter__(self) -> 'ServingFleet':
    return self.start()

  def __exit__(self, *exc_info) -> None:
    self.close()

  def close(self) -> None:
    """Stops reporting/routing, then drains and closes every replica
    (zero drops — each replica's close() answers its whole queue).

    Safe on a fleet that never started, or whose start() failed partway
    (already-spawned replicas and open telemetry streams are released
    either way); idempotent.
    """
    if self._closed:
      return
    self._closed = True
    if self._reporter is not None:
      self._stop.set()
      self._reporter.join()
      self._reporter = None
    self.router.stop()
    if self._started:
      try:
        self._report(force=True)
      except Exception as e:  # noqa: BLE001 — still release the replicas
        log_warning('final fleet report failed: %s', e)
    for replica_id in list(self.router.replica_ids()):
      handle = self.router.remove_replica(replica_id)
      try:
        handle.drain(timeout_s=self.config.drain_timeout_s)
        handle.close()
      except Exception as e:  # noqa: BLE001 — close the rest regardless
        log_warning('replica %s close failed: %s', replica_id, e)
      self._close_replica_telemetry(replica_id)
    if self._telemetry is not None:
      if self._started:
        stats = self.router.stats()
        self._telemetry.log('serving_fleet_stop',
                            rejected_total=stats['rejected_total'],
                            ejections_total=stats['ejections_total'],
                            requests_total=stats['requests_total'])
        self._telemetry.flush()
      self._telemetry.close()
    for logger in self._replica_telemetry.values():
      logger.close()
    self._replica_telemetry.clear()

  # -- request path (the frontend-facing contract) ----------------------------

  def submit(self, features: Dict[str, np.ndarray]) -> Future:
    return self.router.submit(features)

  def select_action(self, features: Dict[str, np.ndarray],
                    timeout_s: Optional[float] = None) -> RoutedResult:
    return self.router.select_action(features, timeout_s=timeout_s)

  def stats(self) -> Dict[str, object]:
    stats = self.router.stats()
    stats['scale_ups_total'] = self._scale_ups.value
    stats['scale_downs_total'] = self._scale_downs.value
    stats['ejected'] = self.router.ejected_ids()
    return stats

  # -- replica lifecycle ------------------------------------------------------

  def _spawn_replica(self) -> Tuple[int, float]:
    with self._lock:
      replica_id = self._next_replica_id
      self._next_replica_id += 1
    telemetry = None
    if self.model_dir is not None:
      # Ids are never reused, so scale-down/up cycles can push an id
      # past max_replicas; the stamped process_count grows with it —
      # an identity must never contradict itself (process_index <
      # process_count, the PR 8 multihost invariant).
      telemetry = TelemetryLogger(
          self.model_dir,
          host_meta=replica_host_meta(
              replica_id, max(self.config.max_replicas, replica_id)))
      self._replica_telemetry[replica_id] = telemetry
    started = self._clock()
    try:
      handle = self._factory(replica_id, telemetry)
    except Exception:
      # A failed spawn (bad artifact, OOM) must not leak an open
      # indexed stream that doctor/discover_hosts would read as a
      # replica that never served. The id stays burned — ids are
      # never reused.
      self._close_replica_telemetry(replica_id, remove_if_empty=True)
      raise
    if handle.replica_id != replica_id:
      handle.replica_id = replica_id
    self.router.add_replica(handle)
    ready_s = self._clock() - started
    return replica_id, ready_s

  def scale_up(self, reason: str = 'manual') -> Tuple[int, float]:
    """Adds one replica; returns ``(replica_id, time_to_ready_s)``.

    Time-to-ready covers the factory (artifact deserialize + server
    start) through rotation entry — the ``fleet_scaleup_time_to_ready_s``
    bench quantity. Raises when the fleet is at ``max_replicas``.
    """
    if len(self.router.replica_ids()) >= self.config.max_replicas:
      raise RuntimeError('fleet already at max_replicas={}'.format(
          self.config.max_replicas))
    replica_id, ready_s = self._spawn_replica()
    self._scale_ups.inc()
    self.last_scaleup_seconds = ready_s
    with self._lock:
      self._scale_events_window += 1
    if self._telemetry is not None:
      self._telemetry.log('serving_fleet_scale', direction='up',
                          replica=replica_id, reason=reason,
                          time_to_ready_s=round(ready_s, 4),
                          replicas_after=len(self.router.replica_ids()))
    return replica_id, ready_s

  def scale_down(self, replica_id: Optional[int] = None,
                 reason: str = 'manual') -> int:
    """Retires one replica: out of rotation first, then drained through
    the close-then-terminate batcher contract — zero dropped requests —
    then closed. Returns the retired id."""
    if len(self.router.replica_ids()) <= self.config.min_replicas:
      raise RuntimeError('fleet already at min_replicas={}'.format(
          self.config.min_replicas))
    if replica_id is None:
      table = self.router.table()
      healthy = self.router.healthy_ids()
      pool = healthy or self.router.replica_ids()
      replica_id = min(pool,
                       key=lambda i: table.get(i, {}).get('outstanding', 0))
    handle = self.router.remove_replica(replica_id)
    drained = handle.drain(timeout_s=self.config.drain_timeout_s)
    handle.close()
    self._close_replica_telemetry(replica_id)
    self._scale_downs.inc()
    with self._lock:
      self._scale_events_window += 1
    if self._telemetry is not None:
      self._telemetry.log('serving_fleet_scale', direction='down',
                          replica=replica_id, reason=reason,
                          drained=bool(drained),
                          replicas_after=len(self.router.replica_ids()))
    return replica_id

  def _close_replica_telemetry(self, replica_id: int,
                               remove_if_empty: bool = False) -> None:
    logger = self._replica_telemetry.pop(replica_id, None)
    if logger is None:
      return
    logger.close()
    if remove_if_empty:
      # A spawn that failed before its first record leaves a 0-byte
      # indexed stream; drop it so the fleet dir only names replicas
      # that existed. A stream with history is always kept.
      try:
        if os.path.getsize(logger.path) == 0:
          os.remove(logger.path)
      except OSError:
        pass

  # -- rolling hot swap -------------------------------------------------------

  def rolling_swap(self, variables: Any, version: int,
                   pause_s: float = 0.0) -> List[int]:
    """Walks the fleet ONE replica at a time onto new weights.

    Each per-replica swap is the PR 7 drain-free protocol (in-flight
    batches finish on the weights they started with), so during the
    wave both versions serve — by construction, not by luck. Returns
    the wave order (replica ids swapped). Replicas whose handle cannot
    swap (a remote replica owned by another orchestrator) are skipped
    with a warning and reported in the wave record.
    """
    wave: List[int] = []
    skipped: List[int] = []
    with self._lock:
      self._last_swap = (variables, int(version))
    for replica_id in self.router.healthy_ids():
      try:
        handle = self.router.handle(replica_id)
      except KeyError:
        continue  # scaled down mid-wave
      try:
        handle.swap_params(variables, version)
        wave.append(replica_id)
      except NotImplementedError:
        skipped.append(replica_id)
        log_warning('rolling swap: replica %s handle cannot swap '
                    '(remote); skipped', replica_id)
      if pause_s > 0:
        time.sleep(pause_s)
    if self._telemetry is not None:
      self._telemetry.log('serving_fleet_swap', version=int(version),
                          wave=wave, skipped=skipped)
    return wave

  # -- reporting + autoscaling ------------------------------------------------

  def _on_router_event(self, kind: str, **payload) -> None:
    if kind == 'eject':
      with self._lock:
        self._ejections_window += 1
    if kind == 'return':
      self._reconcile_swap(payload.get('replica'))
    if self._telemetry is not None:
      self._telemetry.log('serving_fleet_{}'.format(kind), **payload)
      self._telemetry.flush()

  def _reconcile_swap(self, replica_id) -> None:
    """Brings a re-armed replica onto the newest rolling-swap version.

    A replica ejected mid-wave missed its swap; rejoining rotation on
    the OLD weights would silently serve a stale policy until the next
    checkpoint poll. Swapped here, at the re-arm edge, before routing
    weight returns to it in earnest.
    """
    with self._lock:
      last = self._last_swap
    if last is None or replica_id is None:
      return
    variables, version = last
    try:
      handle = self.router.handle(int(replica_id))
      if handle.snapshot().get('params_version') == version:
        return
      handle.swap_params(variables, version)
      log_warning('replica %s re-armed on a stale version; swapped to '
                  'v%s (it missed a rolling wave while ejected)',
                  replica_id, version)
    except KeyError:
      pass  # removed between the event and here
    except NotImplementedError:
      log_warning('replica %s re-armed on a stale version but its '
                  'handle cannot swap (remote orchestrator owns it)',
                  replica_id)

  def _report_loop(self) -> None:
    while not self._stop.wait(self.config.report_interval_s):
      try:
        self._report()
        if self.config.autoscale:
          self._autoscale()
      except Exception as e:  # noqa: BLE001 — reporting/scaling must not
        # take the data path down with it.
        log_warning('ServingFleet report failed (kept serving): %s', e)

  def _report(self, force: bool = False) -> Optional[Dict[str, object]]:
    now = self._clock()
    window_s = now - self._window_started
    if window_s <= 0 and not force:
      return None
    self._window_started = now
    window = self.router.window_stats()
    table = self.router.table()
    with self._lock:
      ejections = self._ejections_window
      scale_events = self._scale_events_window
      self._ejections_window = self._scale_events_window = 0
    replicas: Dict[str, Dict[str, object]] = {}
    versions = set()
    for replica_id, entry in sorted(table.items()):
      replicas[str(replica_id)] = {
          'alive': bool(entry.get('alive')),
          'ejected': bool(entry.get('ejected')),
          'weight': round(float(entry.get('weight') or 0.0), 4),
          'queue_depth': entry.get('queue_depth'),
          'outstanding': entry.get('outstanding'),
          'p99_ms': entry.get('p99_ms'),
          'requests_per_sec': entry.get('requests_per_sec'),
          'requests': entry.get('requests'),
          'over_slo': bool(entry.get('over_slo')),
          'slo_ms': entry.get('slo_ms'),
          'params_version': entry.get('params_version'),
      }
      if not entry.get('ejected') and \
          entry.get('params_version') is not None:
        versions.add(int(entry['params_version']))
    latency = window['latency']
    completed = int(window['completed'])
    p99 = float(latency.get('p99', 0.0) or 0.0)
    stats = self.router.stats()
    record = {
        'schema': SERVING_FLEET_SCHEMA,
        'window_seconds': round(window_s, 3),
        'replica_count': stats['replica_count'],
        'healthy_count': stats['healthy_count'],
        'ejected': self.router.ejected_ids(),
        'replicas': replicas,
        'requests': completed,
        'actions_per_sec': round(completed / window_s, 2)
                           if window_s > 0 else 0.0,
        'retried': int(window['retried']),
        'p50_ms': round(float(latency.get('p50', 0.0) or 0.0), 3),
        'p95_ms': round(float(latency.get('p95', 0.0) or 0.0), 3),
        'p99_ms': round(p99, 3),
        'slo_ms': self.config.slo_ms,
        'over_slo': bool(completed > 0 and p99 > self.config.slo_ms),
        'ejections': ejections,
        'scale_events': scale_events,
        'rejected_total': stats['rejected_total'],
        'ejections_total': stats['ejections_total'],
        'retries_total': stats['retries_total'],
        'scale_ups_total': self._scale_ups.value,
        'scale_downs_total': self._scale_downs.value,
        'versions_serving': sorted(versions),
    }
    self.last_record = record
    if self._telemetry is not None:
      self._telemetry.log(SERVING_FLEET_RECORD_KIND, **record)
      self._telemetry.heartbeat()
      self._telemetry.flush()
    return record

  def _utilization(self) -> float:
    healthy = self.router.healthy_ids()
    if not healthy:
      return 1.0  # nothing in rotation IS maximal demand pressure
    table = self.router.table()
    capacity = 0
    for replica_id in healthy:
      capacity += int(table.get(replica_id, {}).get('max_queue_depth')
                      or 64)
    if capacity <= 0:
      return 0.0
    return self.router.outstanding_total() / float(capacity)

  def _autoscale(self) -> None:
    """One scale decision per report window, with streak hysteresis."""
    util = self._utilization()
    if util >= self.config.scale_up_at:
      self._util_high_streak += 1
      self._util_low_streak = 0
    elif util <= self.config.scale_down_at:
      self._util_low_streak += 1
      self._util_high_streak = 0
    else:
      self._util_high_streak = self._util_low_streak = 0
    replicas = len(self.router.replica_ids())
    if self._util_high_streak >= self.config.scale_windows and \
        replicas < self.config.max_replicas:
      self._util_high_streak = 0
      self.scale_up(reason='autoscale util={:.2f}'.format(util))
    elif self._util_low_streak >= self.config.scale_windows and \
        replicas > self.config.min_replicas:
      self._util_low_streak = 0
      self.scale_down(reason='autoscale util={:.2f}'.format(util))
