"""Deadline batching + admission control, shared by serving/ and replay/.

The request-coalescing machinery ISSUE 8 built for the policy server,
extracted (ISSUE 11 satellite) into one import-light module so the
replay service's sampling front-end reuses it WITHOUT importing the
policy server (or anything that would pull jax). The original homes —
``serving.batcher`` and ``serving.admission`` — re-export everything
here, so existing imports keep working unchanged.

  * ``DeadlineBatcher`` — concurrent requests enqueue into one
    monitor-protected queue; the serve loop pops *megabatches* under two
    knobs: a full batch (``max_batch_size`` pending) dispatches
    IMMEDIATELY, and an under-full batch dispatches as soon as its oldest
    request has waited ``max_wait_ms`` — so burst traffic packs the
    device and trickle traffic is bounded at one wait budget of added
    latency, never parked until a batch happens to fill.
  * ``AdmissionController`` — depth-based load shedding: requests are
    rejected with :class:`RequestRejected` while the pending queue sits
    at ``max_queue_depth``, and every shed request is counted (the
    counter name is per-service: ``serving/rejected`` by default,
    ``replay/rejected`` for the replay front-end).
  * ``pad_batch`` / ``split_outputs`` — an AOT-compiled executable is
    built for ONE batch shape; under-full batches are padded by
    replicating the last real row (well-conditioned numerics —
    zero-stuffing a uint8 camera frame would score a black image, and
    NaN padding would poison reductions). ``split_outputs`` slices
    responses back to the real row count, so a padded row can never leak
    into any response.

All waits use ``time.monotonic`` (the clock is injectable for tests);
nothing here may consult wall-clock time — a deadline that NTP can
extend is not a deadline.
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tensor2robot_tpu.observability import get_registry

__all__ = ['AdmissionController', 'DeadlineBatcher', 'PendingRequest',
           'RequestRejected', 'SERVING_REJECTED_COUNTER', 'pad_batch',
           'split_outputs']

SERVING_REJECTED_COUNTER = 'serving/rejected'


class PendingRequest:
  """One enqueued request: features + the future its caller waits on."""

  __slots__ = ('request_id', 'features', 'future', 'enqueued_at')

  def __init__(self, request_id: int, features: Dict[str, np.ndarray],
               enqueued_at: float):
    self.request_id = request_id
    self.features = features
    self.future: Future = Future()
    self.enqueued_at = enqueued_at


class RequestRejected(RuntimeError):
  """The server is saturated; the caller should back off / retry
  elsewhere. Maps to HTTP 503 in the frontends."""


class AdmissionController:
  """Depth-based load shedding with rejection accounting.

  A service SLO is a promise about the requests you ACCEPT. Once the
  pending queue saturates, every additional admitted request makes every
  queued request later — the p99 collapses for all callers instead of a
  few callers getting a fast, explicit rejection they can retry against
  another replica. ``counter_name`` routes the shed count to the owning
  service's namespace so capacity planning sees exactly how much demand
  each service turned away.
  """

  def __init__(self, max_queue_depth: int, registry=None,
               counter_name: str = SERVING_REJECTED_COUNTER):
    if max_queue_depth < 1:
      raise ValueError('max_queue_depth must be >= 1; got {}.'.format(
          max_queue_depth))
    self.max_queue_depth = int(max_queue_depth)
    registry = registry or get_registry()
    self._rejected = registry.counter(counter_name)

  def admit(self, queue_depth: int) -> None:
    """Raises RequestRejected (and counts it) when the queue is full."""
    if queue_depth >= self.max_queue_depth:
      self._rejected.inc()
      raise RequestRejected(
          'queue saturated ({} pending >= max_queue_depth {}); '
          'request shed'.format(queue_depth, self.max_queue_depth))

  @property
  def rejected_total(self) -> float:
    return self._rejected.value


class DeadlineBatcher:
  """Coalesces requests into dispatchable batches.

  Contract (tests/test_serving.py):
    * burst: with >= ``max_batch_size`` requests pending, ``next_batch``
      returns exactly ``max_batch_size`` of them with NO deadline wait
      (oldest first — FIFO fairness);
    * trickle: an under-full batch is returned once its OLDEST request
      has aged ``max_wait_ms``, never later (modulo scheduler jitter);
    * close(): wakes every waiter; remaining requests drain as final
      (possibly under-full, immediate) batches, then ``next_batch``
      returns None forever — zero requests dropped on shutdown.
  """

  def __init__(self, max_batch_size: int, max_wait_ms: float,
               clock: Callable[[], float] = time.monotonic):
    if max_batch_size < 1:
      raise ValueError('max_batch_size must be >= 1; got {}.'.format(
          max_batch_size))
    if max_wait_ms < 0:
      raise ValueError('max_wait_ms must be >= 0; got {}.'.format(
          max_wait_ms))
    self.max_batch_size = int(max_batch_size)
    self.max_wait_s = float(max_wait_ms) / 1e3
    self._clock = clock
    self._cond = threading.Condition()
    self._queue: List[PendingRequest] = []
    self._closed = False
    self._ids = itertools.count()

  def submit(self, features: Dict[str, np.ndarray],
             admission: Optional[AdmissionController] = None
             ) -> PendingRequest:
    """Enqueues one request; returns it (caller waits on ``.future``).

    ``admission`` is consulted UNDER the queue lock, so the depth check
    and the enqueue are one atomic step — N concurrent submitters at
    depth ``max - 1`` admit exactly one request, not N (TOCTOU-free
    load shedding).
    """
    request = PendingRequest(next(self._ids), features, self._clock())
    with self._cond:
      if self._closed:
        raise RuntimeError('DeadlineBatcher is closed.')
      if admission is not None:
        admission.admit(len(self._queue))  # raises RequestRejected
      self._queue.append(request)
      self._cond.notify_all()
    return request

  def pending_count(self) -> int:
    with self._cond:
      return len(self._queue)

  def next_batch(self, timeout: Optional[float] = None
                 ) -> Optional[List[PendingRequest]]:
    """Blocks until a batch is due (see class contract); returns it.

    Returns None when ``timeout`` seconds pass with nothing due, or —
    terminally — when the batcher is closed and drained.
    """
    deadline = None if timeout is None else self._clock() + timeout
    with self._cond:
      while True:
        if self._queue:
          if len(self._queue) >= self.max_batch_size or self._closed:
            return self._pop_locked()
          wait_left = (self._queue[0].enqueued_at + self.max_wait_s
                       - self._clock())
          if wait_left <= 0:
            return self._pop_locked()
        elif self._closed:
          return None
        else:
          wait_left = None
        if deadline is not None:
          budget = deadline - self._clock()
          if budget <= 0:
            return None
          wait_left = budget if wait_left is None else min(wait_left,
                                                           budget)
        self._cond.wait(wait_left)

  def _pop_locked(self) -> List[PendingRequest]:
    batch = self._queue[:self.max_batch_size]
    del self._queue[:self.max_batch_size]
    self._cond.notify_all()  # a second consumer may have a batch due too
    return batch

  def close(self) -> None:
    with self._cond:
      self._closed = True
      self._cond.notify_all()


def pad_batch(features_list: Sequence[Dict[str, np.ndarray]],
              pad_to: int) -> Tuple[Dict[str, np.ndarray], int]:
  """Stacks per-request feature dicts and pads to a fixed batch size.

  Each request carries ONE state: every feature array is per-request
  (no leading batch dim; scalars allowed). Returns ``(batched, n_real)``
  where every array in ``batched`` has leading dim ``pad_to`` and rows
  ``[n_real:]`` replicate row ``n_real - 1``.

  Raises ValueError on an empty list, on more requests than ``pad_to``,
  and on requests whose feature names disagree — a shape-stable
  executable needs one fixed feature set.
  """
  if not features_list:
    raise ValueError('pad_batch needs at least one request.')
  n_real = len(features_list)
  if n_real > pad_to:
    raise ValueError('Got {} requests for a batch padded to {}.'.format(
        n_real, pad_to))
  names = sorted(features_list[0])
  for features in features_list[1:]:
    if sorted(features) != names:
      raise ValueError(
          'Requests disagree on feature names: {} vs {}.'.format(
              names, sorted(features)))
  batched: Dict[str, np.ndarray] = {}
  for name in names:
    rows = [np.asarray(features[name]) for features in features_list]
    stacked = np.stack(rows, axis=0)
    if n_real < pad_to:
      pad = np.repeat(stacked[-1:], pad_to - n_real, axis=0)
      stacked = np.concatenate([stacked, pad], axis=0)
    batched[name] = stacked
  return batched, n_real


def split_outputs(outputs: Dict[str, np.ndarray], n_real: int
                  ) -> List[Dict[str, np.ndarray]]:
  """Row ``i`` of every output array becomes request ``i``'s response.

  Only rows ``[:n_real]`` are returned — padded rows are discarded here,
  by construction, before any response exists to leak them into.
  """
  per_request: List[Dict[str, np.ndarray]] = [
      {} for _ in range(n_real)]
  for name, value in outputs.items():
    array = np.asarray(value)
    if array.ndim < 1 or array.shape[0] < n_real:
      raise ValueError(
          'Output {!r} has leading dim {} < {} real requests.'.format(
              name, array.shape[:1], n_real))
    for i in range(n_real):
      per_request[i][name] = array[i]
  return per_request
