"""PolicyServer: batched, SLO-tracked policy inference (ISSUE 8).

The front-end the predictors never were: concurrent ``SelectAction``
requests are admitted (or shed), coalesced into padded megabatches by a
deadline-aware batcher, executed through ONE pre-compiled batch program
over an atomically-swapped versioned parameter snapshot, and answered
with per-request latency accounting against an explicit SLO.

Design invariants:

  * **Never compiles.** The server calls whatever ``batch_fn`` it was
    given — normally a :mod:`serving.artifact` AOT executable built at
    startup from the tuning-cache winner. Every batch has the same
    padded shape, so there is nothing left for XLA to specialize at
    request time (the bench asserts this via ``jax/compiles``).
  * **Versioned params, drain-free hot swap.** ``swap_params`` replaces
    one immutable ``(version, variables)`` snapshot reference; a batch
    reads the snapshot ONCE before executing, so in-flight batches
    finish entirely on the weights they started with and every response
    is labeled with the version that actually produced it. Zero requests
    are dropped or mixed across a swap, by construction — no drain
    barrier needed (``drain`` exists for orderly shutdown, not for
    swaps).
  * **SLOs are measured, not asserted.** Per-request and per-batch
    latency land in the ``inference/latency_ms`` histogram family
    (series ``serving_request`` / ``serving_batch``) on SLO-resolution
    bucket edges; ``serving/{queue_depth,batch_size,padding_waste,
    rejected}`` cover the queueing story; a ``kind="serving"`` record in
    ``telemetry.jsonl`` carries the windowed p50/p95/p99 vs ``slo_ms``
    each report interval, which ``t2r_telemetry doctor`` (and the
    ``bin/check_serving_slo`` gate) diagnose offline.

The module itself imports no jax: the hot path is numpy + threads, and
the device program is an injected callable — so the full batching /
swap / SLO contract is testable on any CPU box (tests/test_serving.py).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import numpy as np

from tensor2robot_tpu.observability import (
    DEFAULT_LATENCY_BUCKETS_MS,
    SLO_LATENCY_BUCKETS_MS,
    Histogram,
    TelemetryLogger,
    get_registry,
)
from tensor2robot_tpu.reliability.logutil import log_warning
from tensor2robot_tpu.serving.admission import AdmissionController
from tensor2robot_tpu.serving.batcher import (
    DeadlineBatcher,
    pad_batch,
    split_outputs,
)

__all__ = ['PolicyServer', 'ServeResult', 'ServingConfig',
           'SERVING_RECORD_KIND', 'SERVING_QUEUE_DEPTH_GAUGE',
           'SERVING_BATCH_SIZE_HISTOGRAM', 'SERVING_PADDING_WASTE_COUNTER',
           'SERVING_REQUESTS_COUNTER', 'SERVING_BATCHES_COUNTER',
           'SERVING_ERRORS_COUNTER', 'SERVING_SWAPS_COUNTER',
           'SERVING_VERSION_GAUGE', 'REQUEST_LATENCY_SERIES',
           'BATCH_LATENCY_SERIES']

# Same family the auto-instrumented predictors/policies report into —
# serving is one more labeled series, not a parallel metric namespace.
# (Name duplicated from predictors/abstract_predictor.py so this module
# stays importable without jax.)
INFERENCE_LATENCY_HISTOGRAM = 'inference/latency_ms'
REQUEST_LATENCY_SERIES = 'serving_request'
BATCH_LATENCY_SERIES = 'serving_batch'

SERVING_RECORD_KIND = 'serving'
SERVING_QUEUE_DEPTH_GAUGE = 'serving/queue_depth'
SERVING_BATCH_SIZE_HISTOGRAM = 'serving/batch_size'
SERVING_PADDING_WASTE_COUNTER = 'serving/padding_waste'
SERVING_REQUESTS_COUNTER = 'serving/requests'
SERVING_BATCHES_COUNTER = 'serving/batches'
SERVING_ERRORS_COUNTER = 'serving/errors'
SERVING_SWAPS_COUNTER = 'serving/swaps'
SERVING_VERSION_GAUGE = 'serving/params_version'


@dataclasses.dataclass
class ServingConfig:
  """Knobs for one PolicyServer.

  Attributes:
    max_batch_size: the ONE padded batch shape the executable serves; a
      full batch dispatches immediately.
    max_wait_ms: deadline for under-full batches — the batching latency
      tax a trickle request can pay, ever.
    max_queue_depth: admission-control bound on PENDING requests;
      arrivals beyond it are shed with :class:`RequestRejected`.
    slo_ms: the per-request latency objective (33 ms = the 30 Hz robot
      control envelope); reported against, never enforced by dropping.
    report_interval_s: cadence of ``kind="serving"`` telemetry records.
  """

  max_batch_size: int = 8
  max_wait_ms: float = 5.0
  max_queue_depth: int = 64
  slo_ms: float = 33.0
  report_interval_s: float = 10.0


class ServeResult(NamedTuple):
  """One fulfilled request: outputs + the params version that produced
  them + the request's measured queue-to-response latency."""

  outputs: Dict[str, np.ndarray]
  version: int
  latency_ms: float


class _VersionedParams(NamedTuple):
  """The atomically-swapped snapshot (one reference; never mutated)."""

  version: int
  variables: Any


def _to_numpy(outputs) -> Dict[str, np.ndarray]:
  """Device outputs -> host arrays (np.asarray blocks until ready)."""
  return {k: np.asarray(v) for k, v in dict(outputs).items()}


class PolicyServer:
  """Batches concurrent action requests through one compiled program.

  Args:
    batch_fn: ``(variables, batched_features, seed) -> outputs dict``;
      every array in ``batched_features`` has leading dim
      ``max_batch_size`` and ``seed`` is a ``np.uint32`` scalar (fold it
      into the program's PRNG). Normally an AOT
      :class:`~tensor2robot_tpu.serving.artifact.ServingExecutable`
      executable; any callable with the contract works (tests).
    variables: the initial parameter pytree; ``version`` labels it.
    config: :class:`ServingConfig`.
    model_dir: when set, a ``TelemetryLogger`` writes ``serving_start`` /
      ``serving`` / ``serving_swap`` / ``serving_stop`` records (and
      heartbeats) under it for the doctor; None = metrics-registry only.
    feature_spec: optional ``{name: (shape, dtype)}`` per-request
      contract; submissions are validated and cast against it so a
      malformed request fails ITS caller, never the batch it would have
      ridden in.
    aot_info: provenance dict from the artifact loader, published in the
      ``serving_start`` record (``aot_startup``, ``from_cache``, ...).
  """

  def __init__(self,
               batch_fn: Callable[..., Dict[str, np.ndarray]],
               variables: Any,
               config: Optional[ServingConfig] = None,
               version: int = 0,
               model_dir: Optional[str] = None,
               feature_spec: Optional[Dict[str, Tuple]] = None,
               aot_info: Optional[Dict[str, Any]] = None,
               registry=None,
               telemetry: Optional[TelemetryLogger] = None,
               clock: Callable[[], float] = time.monotonic):
    self.config = config or ServingConfig()
    self._batch_fn = batch_fn
    self._params = _VersionedParams(version=int(version),
                                    variables=variables)
    self._feature_spec = feature_spec
    self._aot_info = dict(aot_info or {})
    self._clock = clock
    self._registry = registry or get_registry()
    self._batcher = DeadlineBatcher(self.config.max_batch_size,
                                    self.config.max_wait_ms, clock=clock)
    self._admission = AdmissionController(self.config.max_queue_depth,
                                          registry=self._registry)
    self._owns_telemetry = telemetry is None and model_dir is not None
    self._telemetry = telemetry
    if self._owns_telemetry:
      self._telemetry = TelemetryLogger(model_dir)

    # Family default = the predictors' default edges, so whichever of
    # predictor/server registers the family first, the config agrees;
    # the serving series override their own edges to SLO resolution.
    latency_family = self._registry.histogram_family(
        INFERENCE_LATENCY_HISTOGRAM, ('predictor',),
        bounds=DEFAULT_LATENCY_BUCKETS_MS)
    self._request_latency = latency_family.series(
        REQUEST_LATENCY_SERIES, bounds=SLO_LATENCY_BUCKETS_MS)
    self._batch_latency = latency_family.series(
        BATCH_LATENCY_SERIES, bounds=SLO_LATENCY_BUCKETS_MS)
    # Fixed 1..256 integer edges (NOT derived from max_batch_size: two
    # servers with different batch shapes share one registry name, and
    # re-registering a histogram with different bounds is an error).
    self._batch_size_hist = self._registry.histogram(
        SERVING_BATCH_SIZE_HISTOGRAM,
        bounds=tuple(float(i) for i in range(1, 257)))
    self._queue_gauge = self._registry.gauge(SERVING_QUEUE_DEPTH_GAUGE)
    self._padding_counter = self._registry.counter(
        SERVING_PADDING_WASTE_COUNTER)
    self._requests_counter = self._registry.counter(
        SERVING_REQUESTS_COUNTER)
    self._batches_counter = self._registry.counter(SERVING_BATCHES_COUNTER)
    self._errors_counter = self._registry.counter(SERVING_ERRORS_COUNTER)
    self._swaps_counter = self._registry.counter(SERVING_SWAPS_COUNTER)
    self._version_gauge = self._registry.gauge(SERVING_VERSION_GAUGE)
    self._version_gauge.set(float(version))

    # Windowed SLO view: reset each report interval; the registry series
    # above stays cumulative for TensorBoard.
    self._window_hist = Histogram(SLO_LATENCY_BUCKETS_MS)
    self._window_lock = threading.Lock()
    self._window_started = self._clock()
    self._window_batches = 0
    self._window_rows = 0
    self._window_padded = 0

    # Fleet-observatory surface (ISSUE 14): the router reads the last
    # closed SLO window (weights) and the report age (liveness) — a
    # serve loop wedged inside a batch stops reporting, which is the
    # same "heartbeat went stale" signal the fleet watchdog keys on.
    self.last_report: Optional[Dict[str, object]] = None
    self._last_report_at = self._clock()

    # Drain accounting: a request is "accepted" at submit and "answered"
    # when its future resolves — so drain() can never observe the gap
    # between a batch leaving the queue and entering execution.
    self._count_lock = threading.Lock()
    self._accepted = 0
    self._answered = 0
    self._batch_index = 0
    self._stop = False
    self._worker: Optional[threading.Thread] = None

  # -- lifecycle -------------------------------------------------------------

  def start(self) -> 'PolicyServer':
    if self._worker is not None:
      raise RuntimeError('PolicyServer already started.')
    if self._telemetry is not None:
      self._telemetry.log(
          'serving_start',
          config={'max_batch_size': self.config.max_batch_size,
                  'max_wait_ms': self.config.max_wait_ms,
                  'max_queue_depth': self.config.max_queue_depth,
                  'slo_ms': self.config.slo_ms},
          params_version=self._params.version, **self._aot_info)
    self._worker = threading.Thread(target=self._serve_loop,
                                    name='t2r-policy-server', daemon=True)
    self._worker.start()
    return self

  def __enter__(self) -> 'PolicyServer':
    return self.start()

  def __exit__(self, *exc_info) -> None:
    self.close()

  def close(self) -> None:
    """Drains pending requests (they are answered, not dropped), stops
    the serve loop, emits the final report + ``serving_stop``."""
    if self._worker is None:
      return
    self._stop = True
    self._batcher.close()
    self._worker.join()
    self._worker = None
    self._report(force=True)
    if self._telemetry is not None:
      self._telemetry.log('serving_stop',
                          params_version=self._params.version,
                          rejected_total=self._admission.rejected_total)
      self._telemetry.flush()
      if self._owns_telemetry:
        self._telemetry.close()
    self._queue_gauge.set(0.0)

  @property
  def alive(self) -> bool:
    """Whether the serve loop thread is running (started, not closed)."""
    return self._worker is not None and self._worker.is_alive()

  def report_age_s(self) -> float:
    """Seconds since the serve loop last closed an SLO report window.

    The in-process heartbeat the fleet router ejects on: a healthy loop
    reports every ``report_interval_s``; a loop wedged inside a hung
    batch (or dead) stops, and this age grows without bound.
    """
    return self._clock() - self._last_report_at

  def drain(self, timeout_s: float = 30.0) -> bool:
    """Blocks until every accepted request has been ANSWERED (True), or
    the timeout passes (False). Shutdown helper — hot swaps do NOT
    drain. Counted from submit to future resolution, so a batch between
    queue and execution still counts as outstanding."""
    deadline = self._clock() + timeout_s
    while self._clock() < deadline:
      with self._count_lock:
        outstanding = self._accepted - self._answered
      if outstanding == 0:
        return True
      time.sleep(0.002)
    return False

  # -- request path ----------------------------------------------------------

  def submit(self, features: Dict[str, np.ndarray]) -> Future:
    """Enqueues one single-state request; returns the Future resolving
    to a :class:`ServeResult`. Raises :class:`RequestRejected` when the
    queue is saturated and ValueError on a spec-violating request."""
    features = self._coerce(features)
    # Depth check and enqueue are one atomic step under the batcher's
    # lock: concurrent submitters cannot all pass the check and
    # overshoot max_queue_depth.
    request = self._batcher.submit(features, admission=self._admission)
    with self._count_lock:
      self._accepted += 1
    self._queue_gauge.set(float(self._batcher.pending_count()))
    return request.future

  def select_action(self, features: Dict[str, np.ndarray],
                    timeout_s: Optional[float] = None) -> ServeResult:
    """Blocking convenience wrapper over :meth:`submit`."""
    return self.submit(features).result(timeout=timeout_s)

  def _coerce(self, features: Dict[str, np.ndarray]
              ) -> Dict[str, np.ndarray]:
    if self._feature_spec is None:
      return dict(features)
    spec_names = set(self._feature_spec)
    got_names = set(features)
    if spec_names != got_names:
      raise ValueError(
          'Request features {} do not match the serving spec {}.'.format(
              sorted(got_names), sorted(spec_names)))
    out: Dict[str, np.ndarray] = {}
    for name, (shape, dtype) in self._feature_spec.items():
      value = np.asarray(features[name], dtype=dtype)
      if tuple(value.shape) != tuple(shape):
        raise ValueError(
            'Feature {!r} has shape {}; the serving spec requires '
            '{} (per request, no batch dim).'.format(
                name, value.shape, tuple(shape)))
      out[name] = value
    return out

  # -- hot swap --------------------------------------------------------------

  @property
  def params_version(self) -> int:
    return self._params.version

  def swap_params(self, variables: Any, version: int) -> None:
    """Replaces the serving weights with zero dropped requests.

    One reference assignment: batches formed after this line read the
    new snapshot; a batch already executing keeps the old one until its
    futures are set (versioned-params contract — the response's
    ``version`` field always names the weights that scored it).
    """
    previous = self._params.version
    self._params = _VersionedParams(version=int(version),
                                    variables=variables)
    self._swaps_counter.inc()
    self._version_gauge.set(float(version))
    if self._telemetry is not None:
      self._telemetry.log('serving_swap', version=int(version),
                          previous_version=previous)

  def swap_from_predictor(self, predictor) -> bool:
    """Adopts a polling predictor's freshly-restored weights (the
    existing hot-swap machinery feeds the server; ISSUE 8 tentpole c).

    Reads the predictor's atomic ``versioned_variables`` snapshot and
    swaps only when the version moved. Call after ``predictor.restore()``
    returns True (e.g. from a poll loop).
    """
    version, variables = predictor.versioned_variables
    if version == self._params.version:
      return False
    self.swap_params(variables, version)
    return True

  # -- serve loop ------------------------------------------------------------

  def _serve_loop(self) -> None:
    while True:
      batch = self._batcher.next_batch(timeout=0.05)
      if batch is None:
        if self._stop:
          break  # closed AND drained (next_batch drains before None)
      else:
        try:
          self._run_batch(batch)
        except Exception as e:  # noqa: BLE001 — the loop must outlive
          # anything: a dead serve thread hangs EVERY future caller.
          # (_run_batch already answers the batch's futures for device
          # failures; this guards the accounting/future plumbing itself.)
          log_warning('PolicyServer serve loop error (kept serving): %s',
                      e)
      try:
        self._maybe_report()
      except Exception as e:  # noqa: BLE001 — telemetry I/O (full disk,
        # yanked model_dir) must degrade to a warning, not kill serving.
        log_warning('PolicyServer report failed (kept serving): %s', e)

  def _run_batch(self, batch) -> None:
    try:
      params = self._params  # ONE snapshot read for the whole batch
      start = self._clock()
      try:
        stacked, n_real = pad_batch([r.features for r in batch],
                                    self.config.max_batch_size)
        seed = np.uint32(self._batch_index & 0xFFFFFFFF)
        self._batch_index += 1
        outputs = _to_numpy(
            self._batch_fn(params.variables, stacked, seed))
        rows = split_outputs(outputs, n_real)
      except Exception as e:  # noqa: BLE001 — answer the callers, keep serving
        self._errors_counter.inc(len(batch))
        log_warning('PolicyServer batch failed (%d requests): %s',
                    len(batch), e)
        for request in batch:
          self._answer(request, error=e)
        return
      end = self._clock()
      batch_ms = (end - start) * 1e3
      self._batch_latency.record(batch_ms)
      self._batch_size_hist.record(float(n_real))
      self._padding_counter.inc(self.config.max_batch_size - n_real)
      self._requests_counter.inc(n_real)
      self._batches_counter.inc()
      with self._window_lock:
        self._window_batches += 1
        self._window_rows += n_real
        self._window_padded += self.config.max_batch_size - n_real
      for request, row in zip(batch, rows):
        latency_ms = (end - request.enqueued_at) * 1e3
        self._request_latency.record(latency_ms)
        with self._window_lock:
          self._window_hist.record(latency_ms)
        self._answer(request,
                     result=ServeResult(outputs=row, version=params.version,
                                        latency_ms=latency_ms))
    finally:
      self._queue_gauge.set(float(self._batcher.pending_count()))

  def _answer(self, request, result=None, error=None) -> None:
    """Resolves one future, tolerating a caller who cancelled it (their
    batch slot was already spent; the loop must not die over it).
    Every accepted request passes through here exactly once — the
    'answered' side of drain()'s accounting."""
    try:
      if error is not None:
        request.future.set_exception(error)
      else:
        request.future.set_result(result)
    except Exception:  # noqa: BLE001 — InvalidStateError on cancel
      pass
    finally:
      with self._count_lock:
        self._answered += 1

  # -- SLO reporting ---------------------------------------------------------

  def _maybe_report(self) -> None:
    if self._clock() - self._window_started >= \
        self.config.report_interval_s:
      self._report()

  def _report(self, force: bool = False) -> None:
    now = self._clock()
    window_s = now - self._window_started
    if window_s <= 0 and not force:
      return
    with self._window_lock:
      summary = self._window_hist.summary()
      self._window_hist.reset()
      batches = self._window_batches
      rows = self._window_rows
      padded = self._window_padded
      self._window_batches = self._window_rows = self._window_padded = 0
      self._window_started = now
    count = int(summary.get('count', 0))
    p99 = summary.get('p99', 0.0)
    record = {
        'window_seconds': round(window_s, 3),
        'requests': count,
        'requests_per_sec': round(count / window_s, 2) if window_s > 0
                            else 0.0,
        'p50_ms': round(summary.get('p50', 0.0), 3),
        'p95_ms': round(summary.get('p95', 0.0), 3),
        'p99_ms': round(p99, 3),
        'slo_ms': self.config.slo_ms,
        'over_slo': bool(count > 0 and p99 > self.config.slo_ms),
        'queue_depth': self._batcher.pending_count(),
        'batch_fill': round(rows / (batches * self.config.max_batch_size),
                            4) if batches else 0.0,
        'padding_waste': padded,
        'rejected_total': self._admission.rejected_total,
        'params_version': self._params.version,
    }
    self.last_report = record
    self._last_report_at = now
    if self._telemetry is not None:
      self._telemetry.log(SERVING_RECORD_KIND, **record)
      self._telemetry.heartbeat()
      self._telemetry.flush()

  # -- introspection ---------------------------------------------------------

  def stats(self) -> Dict[str, object]:
    """Cumulative serving stats (frontend /healthz + bench)."""
    return {
        'requests_total': self._requests_counter.value,
        'batches_total': self._batches_counter.value,
        'rejected_total': self._admission.rejected_total,
        'errors_total': self._errors_counter.value,
        'padding_waste_total': self._padding_counter.value,
        'swaps_total': self._swaps_counter.value,
        'queue_depth': self._batcher.pending_count(),
        'max_queue_depth': self.config.max_queue_depth,
        'params_version': self._params.version,
        'latency_ms': self._request_latency.summary(),
        'batch_size': self._batch_size_hist.summary(),
        'slo_ms': self.config.slo_ms,
    }
