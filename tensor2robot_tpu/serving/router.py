"""Fleet router: telemetry-weighted dispatch over N PolicyServer replicas.

One PolicyServer meets the 33 ms p99 envelope (ISSUE 8); "millions of
users" means aggregate actions/sec must scale with REPLICA COUNT, not
per-server tuning (ROADMAP item 3). This module is the front half of
that story: a router that spreads ``select_action`` requests across a
replica set, using the fleet-observatory signals (per-replica windowed
p99 + queue depth — the same quantities PR 8 federates across hosts) as
its load/health input.

Design invariants:

  * **Weighted least-loaded dispatch.** Each health pass computes a
    routing weight per replica from its last closed SLO window
    (``weight ∝ 1/p99``); each dispatch picks the replica minimizing
    ``outstanding / weight`` — a replica serving at half the latency
    carries twice the depth before it looks equally loaded. Depth is
    the ROUTER'S own outstanding count (submitted minus answered), so
    dispatch never pays a network round trip to ask a replica how busy
    it is.
  * **Shed at the router, before any replica queue.** A fleet-wide
    pending cap (the sum of healthy replicas' ``max_queue_depth`` by
    default) rejects NEW arrivals with :class:`RequestRejected` at the
    door — a saturated fleet answers "503, retry elsewhere" instead of
    letting every queued caller's p99 collapse. Retries of
    already-admitted requests bypass the cap: admission is a promise.
  * **Ejection = the host_dead latch, per replica.** A replica whose
    heartbeat goes stale (its serve loop stopped closing report
    windows, or its /healthz stopped answering) while at least one
    peer is healthy is ejected from rotation — latched, re-armed only
    when it comes back (exactly the PR 8 ``host_dead`` semantics). Its
    in-queue requests are retried EXACTLY ONCE on a healthy peer; the
    replica-side futures are cancelled first, so a zombie replica that
    revives can never deliver a duplicate response (the caller-facing
    Future resolves once, by construction).
  * **Replica handles speak HTTP too.** The router talks to replicas
    only through :class:`ReplicaHandle`; :class:`LocalReplicaHandle`
    wraps an in-process server, :class:`HttpReplicaHandle` speaks the
    PR 7 JSON frontend — multi-host replicas land without any router
    API change.

Jax-free by construction (numpy + threads + stdlib HTTP), like the rest
of serving/: the whole routing/ejection/retry contract tests on CPU.
"""

from __future__ import annotations

import dataclasses
import http.client
import itertools
import json
import threading
import time
from concurrent.futures import Future
from typing import (Any, Callable, Dict, List, NamedTuple, Optional, Set,
                    Tuple)

import numpy as np

from tensor2robot_tpu.observability import (
    DEFAULT_LATENCY_BUCKETS_MS,
    SLO_LATENCY_BUCKETS_MS,
    Histogram,
    get_registry,
)
from tensor2robot_tpu.reliability.logutil import log_warning
from tensor2robot_tpu.serving.batching import RequestRejected
from tensor2robot_tpu.serving.server import PolicyServer, ServeResult

__all__ = ['FleetRouter', 'RouterConfig', 'RoutedResult', 'ReplicaHandle',
           'LocalReplicaHandle', 'HttpReplicaHandle',
           'FLEET_REJECTED_COUNTER', 'FLEET_RETRIES_COUNTER',
           'FLEET_EJECTIONS_COUNTER', 'FLEET_RETURNS_COUNTER',
           'FLEET_REQUESTS_COUNTER', 'FLEET_REPLICAS_GAUGE',
           'FLEET_HEALTHY_GAUGE', 'FLEET_WEIGHT_GAUGE_FAMILY',
           'FLEET_REQUEST_LATENCY_SERIES']

FLEET_REJECTED_COUNTER = 'serving_fleet/rejected'
FLEET_RETRIES_COUNTER = 'serving_fleet/retries'
FLEET_EJECTIONS_COUNTER = 'serving_fleet/ejections'
FLEET_RETURNS_COUNTER = 'serving_fleet/returns'
FLEET_REQUESTS_COUNTER = 'serving_fleet/requests'
FLEET_REPLICAS_GAUGE = 'serving_fleet/replicas'
FLEET_HEALTHY_GAUGE = 'serving_fleet/healthy'
FLEET_WEIGHT_GAUGE_FAMILY = 'serving_fleet/weight'
# Same family as the per-server series (inference/latency_ms): the
# fleet's end-to-end latency is one more labeled series.
INFERENCE_LATENCY_HISTOGRAM = 'inference/latency_ms'
FLEET_REQUEST_LATENCY_SERIES = 'serving_fleet_request'

_DEFAULT_REPLICA_CAPACITY = 64


class RoutedResult(NamedTuple):
  """One fulfilled fleet request.

  ``request_id`` is the router-scoped unique id — the duplicate-
  execution sentinel: however a retry raced a zombie replica, exactly
  one RoutedResult per id ever reaches a caller. ``version`` names the
  params snapshot that scored it (the per-replica contract, preserved);
  ``latency_ms`` is end-to-end at the ROUTER (submit to response),
  which is what the fleet SLO is about; ``replica`` names the replica
  that answered and ``retried`` whether an ejection/overflow re-route
  happened on the way.
  """

  outputs: Dict[str, np.ndarray]
  version: int
  latency_ms: float
  request_id: int
  replica: int
  retried: bool


@dataclasses.dataclass
class RouterConfig:
  """Knobs for one FleetRouter.

  Attributes:
    health_interval_s: cadence of the health/weight pass (snapshots,
      weight recompute, ejection/re-arm).
    stale_after_s: replica report/heartbeat age beyond which it is
      considered dead (ejected while a healthy peer exists). Should be
      a small multiple of the replicas' ``report_interval_s``.
    max_fleet_pending: router-level shed bound; None derives it as the
      sum of healthy replicas' ``max_queue_depth``.
    p99_floor_ms: floor for the 1/p99 weight so one lucky sub-
      microsecond window cannot monopolize routing.
    retry_limit: re-dispatches ONE request may consume (ejection or
      replica-level rejection); 1 = the exactly-once-retry contract.
  """

  health_interval_s: float = 1.0
  stale_after_s: float = 30.0
  max_fleet_pending: Optional[int] = None
  p99_floor_ms: float = 0.5
  retry_limit: int = 1


class _RoutedRequest:
  """Router-side state for one in-flight request."""

  __slots__ = ('request_id', 'features', 'future', 'enqueued_at',
               'retries_left', 'retried', 'replica_future', 'replica')

  def __init__(self, request_id: int, features: Dict[str, np.ndarray],
               enqueued_at: float, retries_left: int):
    self.request_id = request_id
    self.features = features
    self.future: Future = Future()
    self.enqueued_at = enqueued_at
    self.retries_left = retries_left
    self.retried = False
    self.replica_future: Optional[Future] = None
    self.replica: Optional[int] = None


# -- replica handles ----------------------------------------------------------


class ReplicaHandle:
  """What the router needs from one replica, local or remote.

  ``submit`` must return a Future resolving to something with
  ``outputs``/``version``/``latency_ms`` (a :class:`ServeResult`), or
  raise :class:`RequestRejected`/``RuntimeError`` synchronously.
  ``snapshot`` is the health/load read — cheap, never raising (a dead
  replica answers ``alive=False``, it does not throw).
  """

  replica_id: int = -1

  def submit(self, features: Dict[str, np.ndarray]) -> Future:
    raise NotImplementedError

  def snapshot(self) -> Dict[str, Any]:
    raise NotImplementedError

  def swap_params(self, variables: Any, version: int) -> None:
    raise NotImplementedError(
        'replica {} cannot swap params through this handle'.format(
            self.replica_id))

  def drain(self, timeout_s: float = 30.0) -> bool:
    return True

  def close(self) -> None:
    pass


class LocalReplicaHandle(ReplicaHandle):
  """An in-process :class:`PolicyServer` as one fleet replica.

  The health signal is the server's own report cadence: a serve loop
  that stopped closing SLO windows (wedged batch, dead thread) reads as
  a stale heartbeat, exactly like a host that stopped writing
  ``heartbeat.<i>.json``.
  """

  def __init__(self, replica_id: int, server: PolicyServer):
    self.replica_id = int(replica_id)
    self.server = server

  def submit(self, features: Dict[str, np.ndarray]) -> Future:
    return self.server.submit(features)

  def snapshot(self) -> Dict[str, Any]:
    server = self.server
    report = server.last_report or {}
    return {
        'alive': server.alive,
        'heartbeat_age_s': server.report_age_s(),
        'queue_depth': float(report.get('queue_depth', 0) or 0),
        'max_queue_depth': server.config.max_queue_depth,
        'p99_ms': report.get('p99_ms'),
        'requests': report.get('requests'),
        'requests_per_sec': report.get('requests_per_sec'),
        'over_slo': bool(report.get('over_slo')),
        'slo_ms': server.config.slo_ms,
        'params_version': server.params_version,
    }

  def swap_params(self, variables: Any, version: int) -> None:
    self.server.swap_params(variables, version)

  def drain(self, timeout_s: float = 30.0) -> bool:
    return self.server.drain(timeout_s=timeout_s)

  def close(self) -> None:
    self.server.close()


class HttpReplicaHandle(ReplicaHandle):
  """A remote PolicyServer behind the PR 7 HTTP frontend.

  Same contract as a local handle — which is the multi-host story: the
  router's API does not change when replicas leave the process.
  ``submit`` rides a small per-handle thread pool (one blocking POST
  per request); 503 maps back to :class:`RequestRejected`.
  ``snapshot`` is one ``GET /healthz`` — reachability IS the heartbeat
  (``heartbeat_age_s`` 0 when it answers; ``alive=False`` when it does
  not), and the p99 is the server's cumulative view (the windowed
  number still lands in fleet telemetry via the replica's own stream).
  """

  def __init__(self, replica_id: int, host: str, port: int,
               timeout_s: float = 30.0, max_workers: int = 8,
               health_timeout_s: float = 2.0):
    from concurrent.futures import ThreadPoolExecutor

    self.replica_id = int(replica_id)
    self.host = host
    self.port = int(port)
    self.timeout_s = float(timeout_s)
    # Health probes run SERIALLY in the router's health pass: a
    # black-holed remote must cost one short timeout per pass, not the
    # request timeout — otherwise one partitioned replica throttles
    # ejection/re-arm detection for the whole fleet to ~1/timeout Hz.
    self.health_timeout_s = float(health_timeout_s)
    self._pool = ThreadPoolExecutor(
        max_workers=max_workers,
        thread_name_prefix='t2r-replica-{}'.format(replica_id))

  def _request(self, method: str, path: str, payload=None,
               timeout_s: Optional[float] = None):
    conn = http.client.HTTPConnection(
        self.host, self.port,
        timeout=self.timeout_s if timeout_s is None else timeout_s)
    try:
      body = None if payload is None else json.dumps(payload)
      conn.request(method, path, body=body,
                   headers={'Content-Type': 'application/json'})
      response = conn.getresponse()
      return response.status, json.loads(response.read() or b'{}')
    finally:
      conn.close()

  def _post_select_action(self, features: Dict[str, np.ndarray]):
    status, body = self._request(
        'POST', '/v1/select_action',
        {'features': {name: np.asarray(value).tolist()
                      for name, value in features.items()}})
    if status == 503:
      raise RequestRejected(body.get('error', 'replica shed the request'))
    if status != 200:
      raise RuntimeError('replica {} answered {}: {}'.format(
          self.replica_id, status, body.get('error')))
    return ServeResult(
        outputs={name: np.asarray(value)
                 for name, value in body['outputs'].items()},
        version=int(body['version']),
        latency_ms=float(body['latency_ms']))

  def submit(self, features: Dict[str, np.ndarray]) -> Future:
    return self._pool.submit(self._post_select_action, features)

  def snapshot(self) -> Dict[str, Any]:
    try:
      status, stats = self._request('GET', '/healthz',
                                    timeout_s=self.health_timeout_s)
    except (OSError, ValueError) as e:
      return {'alive': False, 'heartbeat_age_s': float('inf'),
              'queue_depth': 0.0, 'max_queue_depth': None, 'p99_ms': None,
              'requests': None, 'requests_per_sec': None, 'over_slo': False,
              'slo_ms': None, 'params_version': None, 'error': str(e)}
    latency = stats.get('latency_ms') or {}
    return {
        'alive': status == 200,
        'heartbeat_age_s': 0.0,
        'queue_depth': float(stats.get('queue_depth', 0) or 0),
        'max_queue_depth': stats.get('max_queue_depth'),
        'p99_ms': latency.get('p99'),
        'requests': stats.get('requests_total'),
        'requests_per_sec': None,
        'over_slo': False,
        'slo_ms': stats.get('slo_ms'),
        'params_version': stats.get('params_version'),
    }

  def close(self) -> None:
    self._pool.shutdown(wait=False)


# -- the router ---------------------------------------------------------------


class FleetRouter:
  """Spreads requests over replica handles; ejects the dead; retries once.

  Args:
    handles: initial replicas (add/remove later via
      :meth:`add_replica` / :meth:`remove_replica`).
    config: :class:`RouterConfig`.
    on_event: optional callback ``(kind, **payload)`` for lifecycle
      events (``eject``/``return``) — the fleet wires this into its
      telemetry stream; the router itself owns no files.
  """

  def __init__(self, handles: List[ReplicaHandle],
               config: Optional[RouterConfig] = None,
               on_event: Optional[Callable[..., None]] = None,
               registry=None,
               clock: Callable[[], float] = time.monotonic):
    self.config = config or RouterConfig()
    self._clock = clock
    self._on_event = on_event
    self._registry = registry or get_registry()
    # RLock: Future.cancel()/set_result() invoke done-callbacks
    # synchronously on the calling thread, and _on_replica_done re-takes
    # the lock the ejection pass already holds.
    self._lock = threading.RLock()
    self._handles: Dict[int, ReplicaHandle] = {}
    self._ejected: Set[int] = set()
    self._weights: Dict[int, float] = {}
    self._last_p99: Dict[int, float] = {}  # survives idle (empty) windows
    self._capacity: Dict[int, int] = {}
    self._outstanding: Dict[int, Dict[int, _RoutedRequest]] = {}
    self._snapshots: Dict[int, Dict[str, Any]] = {}
    self._ids = itertools.count()

    self._rejected = self._registry.counter(FLEET_REJECTED_COUNTER)
    self._retries = self._registry.counter(FLEET_RETRIES_COUNTER)
    self._ejections = self._registry.counter(FLEET_EJECTIONS_COUNTER)
    self._returns = self._registry.counter(FLEET_RETURNS_COUNTER)
    self._requests = self._registry.counter(FLEET_REQUESTS_COUNTER)
    self._replicas_gauge = self._registry.gauge(FLEET_REPLICAS_GAUGE)
    self._healthy_gauge = self._registry.gauge(FLEET_HEALTHY_GAUGE)
    self._weight_family = self._registry.gauge_family(
        FLEET_WEIGHT_GAUGE_FAMILY, ('replica',))
    # Family default = the predictors' default edges (whoever registers
    # the family first must agree — same rule as server.py); only the
    # fleet's own series runs on SLO-resolution edges.
    latency_family = self._registry.histogram_family(
        INFERENCE_LATENCY_HISTOGRAM, ('predictor',),
        bounds=DEFAULT_LATENCY_BUCKETS_MS)
    self._latency = latency_family.series(
        FLEET_REQUEST_LATENCY_SERIES, bounds=SLO_LATENCY_BUCKETS_MS)

    # Windowed fleet view, reset each report (the fleet record's input).
    self._window_lock = threading.Lock()
    self._window_hist = Histogram(SLO_LATENCY_BUCKETS_MS)
    self._window_completed = 0
    self._window_retried = 0

    for handle in handles:
      self.add_replica(handle)

    self._stop = threading.Event()
    self._monitor: Optional[threading.Thread] = None

  # -- lifecycle --------------------------------------------------------------

  def start(self) -> 'FleetRouter':
    if self._monitor is not None:
      raise RuntimeError('FleetRouter already started.')
    self.observe()  # arm weights/capacities before the first dispatch
    self._monitor = threading.Thread(target=self._monitor_loop,
                                     name='t2r-fleet-router', daemon=True)
    self._monitor.start()
    return self

  def stop(self) -> None:
    self._stop.set()
    if self._monitor is not None:
      self._monitor.join()
      self._monitor = None

  def _monitor_loop(self) -> None:
    while not self._stop.wait(self.config.health_interval_s):
      try:
        self.observe()
      except Exception as e:  # noqa: BLE001 — health passes must outlive
        # anything; a dead monitor silently freezes weights and ejection.
        log_warning('FleetRouter health pass failed (kept routing): %s', e)

  # -- replica set ------------------------------------------------------------

  def add_replica(self, handle: ReplicaHandle) -> None:
    with self._lock:
      if handle.replica_id in self._handles:
        raise ValueError('replica id {} already routed'.format(
            handle.replica_id))
      self._handles[handle.replica_id] = handle
      self._outstanding.setdefault(handle.replica_id, {})
      # Enter at the peers' MEAN weight, not 1.0: post-observe weights
      # are normalized to sum 1, and a 1.0 entry would make a freshly
      # scaled-up replica look ~N x less loaded than its equally-idle
      # peers — dogpiling it until the next health pass, at exactly the
      # high-load moment that triggered the scale-up.
      active = [w for rid, w in self._weights.items()
                if rid in self._handles and w > 0]
      self._weights.setdefault(
          handle.replica_id,
          (sum(active) / len(active)) if active else 1.0)
      self._capacity.setdefault(handle.replica_id,
                                _DEFAULT_REPLICA_CAPACITY)
      self._replicas_gauge.set(float(len(self._handles)))

  def remove_replica(self, replica_id: int) -> ReplicaHandle:
    """Takes a replica out of rotation (scale-down path).

    New dispatches stop immediately; requests already queued on it stay
    with it — the caller drains the handle (zero drops, the PR 7
    close-then-terminate contract) before closing it.
    """
    with self._lock:
      handle = self._handles.pop(replica_id)
      self._ejected.discard(replica_id)
      self._outstanding.pop(replica_id, None)
      self._weights.pop(replica_id, None)
      self._last_p99.pop(replica_id, None)
      self._capacity.pop(replica_id, None)
      self._snapshots.pop(replica_id, None)
      self._replicas_gauge.set(float(len(self._handles)))
    return handle

  def replica_ids(self) -> List[int]:
    with self._lock:
      return sorted(self._handles)

  def healthy_ids(self) -> List[int]:
    with self._lock:
      return sorted(set(self._handles) - self._ejected)

  def ejected_ids(self) -> List[int]:
    with self._lock:
      return sorted(self._ejected)

  def handle(self, replica_id: int) -> ReplicaHandle:
    with self._lock:
      return self._handles[replica_id]

  # -- request path -----------------------------------------------------------

  def submit(self, features: Dict[str, np.ndarray]) -> Future:
    """Routes one request; returns the Future resolving to a
    :class:`RoutedResult`. Raises :class:`RequestRejected` on fleet-wide
    shed and RuntimeError when no replica is in rotation."""
    routed = _RoutedRequest(next(self._ids), dict(features),
                            self._clock(), self.config.retry_limit)
    self._dispatch(routed, admit=True)
    self._requests.inc()
    return routed.future

  def select_action(self, features: Dict[str, np.ndarray],
                    timeout_s: Optional[float] = None) -> RoutedResult:
    return self.submit(features).result(timeout=timeout_s)

  def _fleet_capacity_locked(self, healthy: List[int]) -> int:
    if self.config.max_fleet_pending is not None:
      return int(self.config.max_fleet_pending)
    return sum(self._capacity.get(i) or _DEFAULT_REPLICA_CAPACITY
               for i in healthy)

  def _pick_locked(self, healthy: List[int],
                   exclude: Set[int]) -> Optional[int]:
    candidates = [i for i in healthy if i not in exclude]
    if not candidates:
      return None
    # Weighted least-loaded: depth normalized by the telemetry weight.
    # +1 biases an idle tie toward the higher-weight (faster) replica.
    return min(candidates,
               key=lambda i: (len(self._outstanding[i]) + 1)
               / max(self._weights.get(i, 1.0), 1e-9))

  def _dispatch(self, routed: _RoutedRequest, admit: bool,
                exclude: Optional[Set[int]] = None) -> None:
    exclude = set(exclude or ())
    while True:
      with self._lock:
        healthy = [i for i in self._handles if i not in self._ejected]
        if not healthy:
          raise RuntimeError('no replicas in rotation')
        if admit:
          total = sum(len(self._outstanding[i]) for i in healthy)
          if total >= self._fleet_capacity_locked(healthy):
            # The shed decision, at the router: no replica queue was
            # touched for this request. Retries (admit=False) bypass —
            # an admitted request is a promise.
            self._rejected.inc()
            raise RequestRejected(
                'fleet saturated ({} pending >= capacity {}); request '
                'shed at the router'.format(
                    total, self._fleet_capacity_locked(healthy)))
          # Admitted: a later loop iteration (retrying a replica-level
          # rejection) must not re-face the cap — the promise holds
          # even if the fleet filled up in between.
          admit = False
        replica = self._pick_locked(healthy, exclude)
        if replica is None:
          raise RequestRejected(
              'every healthy replica rejected or is excluded for this '
              'request')
        handle = self._handles[replica]
        self._outstanding[replica][routed.request_id] = routed
        routed.replica = replica
      try:
        replica_future = handle.submit(routed.features)
      except Exception as e:  # noqa: BLE001 — classify below
        with self._lock:
          # .get(): the replica may have been REMOVED (scale-down racing
          # a submit against its mid-shutdown server) — the original
          # rejection must win, not a KeyError from the cleanup.
          self._outstanding.get(replica, {}).pop(routed.request_id, None)
        if isinstance(e, (RequestRejected, RuntimeError)) and \
            routed.retries_left > 0:
          # One replica-level rejection (its queue filled between the
          # router's cap check and the enqueue, or it is mid-shutdown):
          # spend the retry budget on a different replica.
          routed.retries_left -= 1
          routed.retried = True
          self._retries.inc()
          exclude.add(replica)
          continue
        # Spec violations (ValueError) and exhausted budgets fail THIS
        # caller synchronously — the single-server contract, preserved.
        raise e
      with self._lock:
        # Entry may have been cleared by a concurrent ejection pass (or
        # the replica removed) between submit and here; only attach the
        # future if we still own the slot.
        owned = self._outstanding.get(replica, {}).get(
            routed.request_id) is routed
        if owned:
          routed.replica_future = replica_future
      if owned:
        replica_future.add_done_callback(
            lambda f, r=routed, i=replica: self._on_replica_done(r, i, f))
      else:
        # An ejection pass raced this submit and already re-routed the
        # request: withdraw the replica-side copy so a revived zombie
        # cannot execute it (a copy already executing still cannot
        # double-deliver — _resolve is single-assignment).
        replica_future.cancel()
      return

  def _on_replica_done(self, routed: _RoutedRequest, replica: int,
                       future: Future) -> None:
    with self._lock:
      entry = self._outstanding.get(replica, {})
      if entry.get(routed.request_id) is routed:
        del entry[routed.request_id]
    if future.cancelled():
      return  # an ejection pass took this request and re-routed it
    try:
      error = future.exception()
    except Exception as e:  # noqa: BLE001 — CancelledError race
      error = e
    if error is not None:
      # An HTTP replica's shed arrives HERE (its submit never raises
      # synchronously — the 503 resolves the pool future): give it the
      # same one-retry-on-a-peer semantics as a synchronous replica
      # rejection. Batch failures (anything else) propagate to the
      # caller, the single-server contract.
      if isinstance(error, RequestRejected) and routed.retries_left > 0:
        routed.retries_left -= 1
        routed.retried = True
        self._retries.inc()
        try:
          self._dispatch(routed, admit=False, exclude={replica})
        except Exception as e:  # noqa: BLE001 — no peer left
          self._resolve(routed, error=e)
        return
      self._resolve(routed, error=error)
      return
    result = future.result()
    latency_ms = (self._clock() - routed.enqueued_at) * 1e3
    self._latency.record(latency_ms)
    with self._window_lock:
      self._window_hist.record(latency_ms)
      self._window_completed += 1
      if routed.retried:
        self._window_retried += 1
    self._resolve(routed, result=RoutedResult(
        outputs=result.outputs, version=result.version,
        latency_ms=latency_ms, request_id=routed.request_id,
        replica=replica, retried=routed.retried))

  def _resolve(self, routed: _RoutedRequest, result=None,
               error=None) -> None:
    """Resolves the caller-facing future AT MOST ONCE (a zombie replica
    racing a retry loses; a cancelled caller is tolerated)."""
    try:
      if error is not None:
        routed.future.set_exception(error)
      else:
        routed.future.set_result(result)
    except Exception:  # noqa: BLE001 — InvalidStateError: already
      pass  # answered by the other contender, or cancelled by caller

  # -- health / weights / ejection -------------------------------------------

  def observe(self) -> Dict[int, Dict[str, Any]]:
    """One health pass: snapshot replicas, recompute weights, eject the
    stale, re-arm the returned, retry the ejected replicas' in-queue
    requests. Returns the snapshots (the fleet record's raw input)."""
    with self._lock:
      handles = dict(self._handles)
    snapshots: Dict[int, Dict[str, Any]] = {}
    for replica_id, handle in sorted(handles.items()):
      try:
        snapshots[replica_id] = handle.snapshot()
      except Exception as e:  # noqa: BLE001 — a throwing snapshot IS dead
        snapshots[replica_id] = {'alive': False,
                                 'heartbeat_age_s': float('inf'),
                                 'p99_ms': None, 'queue_depth': 0.0,
                                 'max_queue_depth': None,
                                 'error': str(e)}
    stale = self.config.stale_after_s
    to_retry: List[_RoutedRequest] = []
    events: List[Tuple[str, Dict[str, Any]]] = []  # emitted post-lock
    with self._lock:
      healthy_now = []
      for replica_id, snap in snapshots.items():
        if replica_id not in self._handles:
          continue  # removed between snapshot and here
        dead = (not snap.get('alive')) or \
            float(snap.get('heartbeat_age_s') or 0.0) > stale
        if not dead:
          healthy_now.append(replica_id)
      for replica_id, snap in sorted(snapshots.items()):
        if replica_id not in self._handles:
          continue
        dead = replica_id not in healthy_now
        if dead and replica_id not in self._ejected and \
            any(h != replica_id for h in healthy_now):
          # Eject: latched, like host_dead — fired once, re-armed only
          # on return. Needs >= 1 healthy peer (all-dead is a fleet
          # outage the doctor pages on, not a routing decision).
          self._ejected.add(replica_id)
          self._ejections.inc()
          pending = list(self._outstanding[replica_id].values())
          self._outstanding[replica_id].clear()
          for routed in pending:
            # Cancel the replica-side future FIRST: a zombie that
            # revives finds a cancelled future (the server's _answer
            # tolerates it) and can never double-deliver.
            if routed.replica_future is not None:
              routed.replica_future.cancel()
          to_retry.extend(pending)
          events.append(('eject',
                         {'replica': replica_id,
                          'heartbeat_age_s': snap.get('heartbeat_age_s'),
                          'in_queue_retried': len(pending)}))
        elif not dead and replica_id in self._ejected:
          self._ejected.discard(replica_id)
          self._returns.inc()
          events.append(('return', {'replica': replica_id}))
      for replica_id, snap in snapshots.items():
        if snap.get('max_queue_depth'):
          self._capacity[replica_id] = int(snap['max_queue_depth'])
      self._update_weights_locked(snapshots)
      self._snapshots = snapshots
      self._healthy_gauge.set(
          float(len(set(self._handles) - self._ejected)))
    # Events fire OUTSIDE the dispatch lock: the fleet's callback does
    # telemetry I/O and (on 'return') a version-reconcile that may read
    # a remote replica — none of which may stall submit()/dispatch.
    for kind, payload in events:
      self._emit(kind, **payload)
    for routed in to_retry:
      if routed.future.done():
        continue  # answered (or cancelled by its caller) already
      if routed.retries_left <= 0:
        self._resolve(routed, error=RuntimeError(
            'replica died and the retry budget is spent'))
        continue
      routed.retries_left -= 1
      routed.retried = True
      self._retries.inc()
      try:
        self._dispatch(routed, admit=False)  # admitted once already
      except Exception as e:  # noqa: BLE001 — no healthy peer left
        self._resolve(routed, error=e)
    return snapshots

  def _update_weights_locked(self,
                             snapshots: Dict[int, Dict[str, Any]]) -> None:
    floor = self.config.p99_floor_ms
    raw: Dict[int, float] = {}
    for replica_id in self._handles:
      if replica_id in self._ejected:
        continue
      p99 = (snapshots.get(replica_id) or {}).get('p99_ms')
      if p99:
        # Only a window that SERVED updates the signal: an idle (empty)
        # window reports p99 0, which is "no evidence", not "infinitely
        # fast" — the last traffic-bearing window's weight persists.
        self._last_p99[replica_id] = float(p99)
      if self._last_p99.get(replica_id):
        raw[replica_id] = 1.0 / max(self._last_p99[replica_id], floor)
    if raw:
      # Replicas with no window yet (just scaled up) enter at the
      # healthy median, not at a made-up extreme.
      median = sorted(raw.values())[len(raw) // 2]
    else:
      median = 1.0
    total = 0.0
    weights: Dict[int, float] = {}
    for replica_id in self._handles:
      if replica_id in self._ejected:
        weights[replica_id] = 0.0
        continue
      weights[replica_id] = raw.get(replica_id, median)
      total += weights[replica_id]
    if total > 0:
      for replica_id in weights:
        weights[replica_id] /= total
    self._weights = weights
    for replica_id, weight in weights.items():
      self._weight_family.series(str(replica_id)).set(weight)

  def _emit(self, kind: str, **payload) -> None:
    if self._on_event is None:
      return
    try:
      self._on_event(kind, **payload)
    except Exception as e:  # noqa: BLE001 — telemetry must not kill routing
      log_warning('FleetRouter event callback failed: %s', e)

  # -- introspection ----------------------------------------------------------

  def outstanding_total(self) -> int:
    with self._lock:
      return sum(len(v) for v in self._outstanding.values())

  def table(self) -> Dict[int, Dict[str, Any]]:
    """Per-replica routing view: the fleet record's replica table."""
    with self._lock:
      out: Dict[int, Dict[str, Any]] = {}
      for replica_id in sorted(self._handles):
        snap = dict(self._snapshots.get(replica_id) or {})
        snap['weight'] = self._weights.get(replica_id, 0.0)
        snap['outstanding'] = len(self._outstanding[replica_id])
        snap['ejected'] = replica_id in self._ejected
        out[replica_id] = snap
      return out

  def window_stats(self) -> Dict[str, Any]:
    """Reset-on-read window counters + latency summary for one fleet
    report interval."""
    with self._window_lock:
      summary = self._window_hist.summary()
      self._window_hist.reset()
      completed = self._window_completed
      retried = self._window_retried
      self._window_completed = self._window_retried = 0
    return {'completed': completed, 'retried': retried,
            'latency': summary}

  def stats(self) -> Dict[str, Any]:
    """Cumulative router stats (frontend /healthz + bench)."""
    with self._lock:
      replica_count = len(self._handles)
      healthy = len(set(self._handles) - self._ejected)
      outstanding = sum(len(v) for v in self._outstanding.values())
    return {
        'replica_count': replica_count,
        'healthy_count': healthy,
        'queue_depth': outstanding,
        'requests_total': self._requests.value,
        'rejected_total': self._rejected.value,
        'retries_total': self._retries.value,
        'ejections_total': self._ejections.value,
        'returns_total': self._returns.value,
        'latency_ms': self._latency.summary(),
        'params_version': max(
            [int(s.get('params_version') or 0)
             for s in self._snapshots.values()] or [0]),
    }
