"""Runnable serving-fleet bench: aggregate throughput-at-SLO vs replicas.

``python -m tensor2robot_tpu.serving.fleet_bench`` stands up a
``ServingFleet`` of 1 / 2 / 4 PolicyServer replicas behind the
telemetry-weighted router and prints ONE JSON line carrying the
``SERVING_FLEET_BENCH_KEYS`` quantities (serving/fleet.py; schema-locked
by bin/check_serving_slo). ``bench.py`` runs it in a SUBPROCESS because
the CPU leg needs a process-level XLA knob:

**Why a subprocess + ``--xla_cpu_multi_thread_eigen=false`` on CPU.**
XLA:CPU parallelizes ONE executable across the whole core pool; N
concurrent replica executions then fight each other (and the client
threads) for the same cores, so the 4-replica batch time inflates ~2x
and the scaling curve measures scheduler thrash, not routing. Serving
deployments pin intra-op parallelism down for exactly this reason —
throughput-oriented batching wants N independent single-core(ish)
executions, not one N-core execution at a time. The flag is read at
backend init, hence the fresh process. On TPU the executable owns its
chip and no flag is needed.

The policy program is the sim critic's one-dispatch CEM selector
(``rl.loop.make_cem_select_fn`` — the flagship's spec keys, sized for
the CPU envelope): this axis measures the FLEET (routing, scale-out,
rolling swap), and needs a program whose single-replica p99 sits inside
the 33 ms SLO on CPU so the curve is a routing fact. The flagship's
full-resolution single-server numbers are the adjacent ``serving_*``
bench axis.

Contracts measured, not asserted:
  * ``serving_fleet_request_time_compiles`` — ``jax/compiles`` delta
    across every load phase (must be 0: replicas execute one AOT
    program).
  * ``serving_fleet_scaleup_compiles`` — delta across the 4-replica
    run's artifact-warm scale-out from 1 -> 4 replicas (must be 0: each
    new replica deserializes the persisted ``CompiledArtifact``).
  * ``fleet_scaleup_time_to_ready_s`` — slowest artifact-warm scale-up,
    factory start through rotation entry.
  * ``serving_fleet_swap_failed`` / ``..._swap_versions_served`` — the
    mid-load rolling swap: zero failed requests fleet-wide, both
    versions observed serving.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time


def build_sim_batch_select(height: int, width: int, cem_samples: int,
                           cem_iters: int, num_elites: int):
  """(jitted batch_select, variables, feature_spec) for the sim critic.

  Shared by the bench runnable and tests/test_serving_fleet.py's slow
  end-to-end check — one definition of the fleet's policy program.
  """
  import jax
  import jax.numpy as jnp
  import numpy as np

  from tensor2robot_tpu.data.input_generators import (
      DefaultRandomInputGenerator,
  )
  from tensor2robot_tpu.modes import ModeKeys
  from tensor2robot_tpu.research.qtopt import grasping_sim
  from tensor2robot_tpu.rl.loop import make_cem_select_fn

  model = grasping_sim.make_sim_critic_model(height=height, width=width)
  select = make_cem_select_fn(model, cem_samples=cem_samples,
                              cem_iters=cem_iters, num_elites=num_elites)
  batched = jax.vmap(select, in_axes=(None, 0, 0))

  def batch_select(variables, states, seed):
    rows = jax.tree_util.tree_leaves(states)[0].shape[0]
    keys = jax.vmap(
        lambda i: jax.random.fold_in(jax.random.PRNGKey(seed), i))(
            jnp.arange(rows, dtype=jnp.uint32))
    actions, q = batched(variables, dict(states), keys)
    return {'action': actions, 'q': q}

  generator = DefaultRandomInputGenerator(batch_size=2)
  generator.set_specification_from_model(model, ModeKeys.TRAIN)
  features, labels = next(
      generator.create_dataset_iterator(mode=ModeKeys.TRAIN, seed=0))
  feats_p, labels_p = model.preprocessor.preprocess(
      features, labels, ModeKeys.EVAL)
  variables = model.init_variables(jax.random.PRNGKey(0), feats_p,
                                   labels_p, ModeKeys.EVAL)
  feature_spec = {
      'image': ((height, width, 3), np.uint8),
      'gripper_closed': ((), np.float32),
      'height_to_bottom': ((), np.float32),
  }
  return jax.jit(batch_select), variables, feature_spec


def run_bench(batch: int = 8, height: int = 96, width: int = 128,
              cem_samples: int = 32, cem_iters: int = 2,
              num_elites: int = 8, duration_s: float = 3.0,
              replica_counts=(1, 2, 4)) -> dict:
  import jax
  import numpy as np

  from tensor2robot_tpu.observability import (
      TelemetryRegistry,
      get_registry,
      install_jax_listeners,
  )
  from tensor2robot_tpu.observability.signals import COMPILE_COUNTER
  from tensor2robot_tpu.serving import (
      LocalReplicaHandle,
      PolicyServer,
      ServingConfig,
      ServingFleet,
      ServingFleetConfig,
      load_or_compile,
  )
  from tensor2robot_tpu.tuning import cache as cache_lib

  jitted, variables, feature_spec = build_sim_batch_select(
      height, width, cem_samples, cem_iters, num_elites)
  abstract_args = (
      jax.tree_util.tree_map(
          lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), variables),
      {name: jax.ShapeDtypeStruct((batch,) + shape, np.dtype(dtype))
       for name, (shape, dtype) in feature_spec.items()},
      jax.ShapeDtypeStruct((), 'uint32'))

  install_jax_listeners()
  compile_counter = get_registry().counter(COMPILE_COUNTER)
  cache = cache_lib.ConfigCache(
      os.path.join(tempfile.mkdtemp(prefix='fleet_bench_'),
                   'tuning_cache.json'))
  workload = 'serving_fleet_sim_cem_b{}'.format(batch)
  # The ONE startup compile; every replica after this is a store hit.
  load_or_compile(workload, jitted, abstract_args, cache=cache)

  serving_config = ServingConfig(max_batch_size=batch, max_wait_ms=2.0,
                                 max_queue_depth=8 * batch, slo_ms=33.0,
                                 report_interval_s=0.5)
  warm_state = {
      name: np.zeros((batch,) + shape, dtype)
      for name, (shape, dtype) in feature_spec.items()}

  def run_fleet(replicas, with_swap=False, scale_from_one=False):
    registry = TelemetryRegistry()  # per-fleet: p99 must not mix runs

    def factory(replica_id, telemetry):
      artifact = load_or_compile(workload, jitted, abstract_args,
                                 cache=cache)
      # One warm batch BEFORE the replica enters rotation: the first
      # dispatch of a deserialized executable pays one-time runtime
      # setup, which is readiness cost (it stays inside
      # time_to_ready_s), not request latency.
      jax.block_until_ready(
          artifact.executable(variables, warm_state, np.uint32(0)))
      server = PolicyServer(
          artifact.executable, variables, serving_config, version=1,
          telemetry=telemetry, feature_spec=feature_spec,
          registry=registry,
          aot_info={'aot_startup': True,
                    'from_cache': artifact.from_cache})
      server.start()
      return LocalReplicaHandle(replica_id, server)

    config = ServingFleetConfig(
        min_replicas=1, max_replicas=replicas, autoscale=False,
        report_interval_s=0.5, health_interval_s=0.2,
        stale_after_s=10.0, slo_ms=33.0)
    fleet_dir = tempfile.mkdtemp()
    fleet = ServingFleet(
        factory, config, model_dir=fleet_dir,
        initial_replicas=1 if scale_from_one else replicas,
        registry=registry)
    fleet.start()
    scaleup_seconds = []
    compiles_before_scaleup = compile_counter.value
    if scale_from_one:
      for _ in range(replicas - 1):
        _, ready_s = fleet.scale_up(reason='bench')
        scaleup_seconds.append(ready_s)
    scaleup_compiles = compile_counter.value - compiles_before_scaleup

    stop = threading.Event()
    completed = [0]
    versions = set()
    failures = []
    lock = threading.Lock()

    def client(seed):
      client_rng = np.random.RandomState(seed)
      state = {'image': client_rng.randint(0, 255, (height, width, 3)
                                           ).astype(np.uint8),
               'gripper_closed': np.float32(0.0),
               'height_to_bottom': np.float32(0.1)}
      while not stop.is_set():
        try:
          result = fleet.select_action(state, timeout_s=120.0)
          with lock:
            completed[0] += 1
            versions.add(result.version)
        except Exception as e:  # noqa: BLE001 — every failure is the metric
          with lock:
            failures.append(repr(e)[:120])

    # 1.25x each replica's batch in closed-loop clients: enough
    # pressure to keep every batcher fed (the curve measures capacity,
    # not demand) without queueing so deep that the client threads'
    # own GIL contention becomes the thing measured.
    clients = max(batch, (5 * batch * replicas) // 4)
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    compiles_before = compile_counter.value
    start = time.perf_counter()
    for t in threads:
      t.start()
    if with_swap:
      time.sleep(duration_s / 2)
      # Same weights re-labeled v2 (what a trainer checkpoint poll
      # does), walked across the fleet one replica at a time.
      fleet.rolling_swap(variables, version=2)
      time.sleep(duration_s / 2)
    else:
      time.sleep(duration_s)
    stop.set()
    for t in threads:
      t.join()
    elapsed = time.perf_counter() - start
    request_compiles = compile_counter.value - compiles_before
    stats = fleet.stats()
    fleet.close()
    # The published p99 is the MEDIAN steady-state WINDOW p99 from the
    # fleet's own t2r.serving_fleet.v1 records — the same windowed
    # quantity the live SLO monitoring (and doctor) judge. A cumulative
    # whole-run p99 on a seconds-long CPU run is one scheduler stall
    # away from a 3x outlier, which would measure the container's
    # ambient load, not the fleet.
    from tensor2robot_tpu.observability import read_telemetry
    window_p99s = [
        r['p99_ms'] for r in read_telemetry(
            os.path.join(fleet_dir, 'telemetry.0.jsonl'))
        if r.get('kind') == 'serving_fleet'
        and (r.get('requests') or 0) >= 100]
    if window_p99s:
      p99 = sorted(window_p99s)[len(window_p99s) // 2]
    else:
      p99 = stats['latency_ms'].get('p99', 0.0)
    return {
        'replicas': replicas,
        'actions_per_sec': round(completed[0] / elapsed, 2),
        'p99_ms': round(p99, 2),
        'p99_ms_cumulative': round(
            stats['latency_ms'].get('p99', 0.0), 2),
        'window_p99s_ms': [round(p, 2) for p in window_p99s],
        'slo_met': bool(completed[0] > 0 and p99 < 33.0),
        'failed': len(failures),
        'versions_served': sorted(versions),
        'request_time_compiles': request_compiles,
        'scaleup_compiles': scaleup_compiles,
        'scaleup_seconds': [round(s, 4) for s in scaleup_seconds],
        'clients': clients,
    }

  counts = sorted(replica_counts)
  runs = {}
  for n in counts:
    biggest = n == counts[-1] and n > 1
    runs[n] = run_fleet(n, with_swap=biggest, scale_from_one=biggest)
  curve = [runs[n]['actions_per_sec'] for n in counts]
  top = runs[counts[-1]]
  out = {
      'serving_fleet_scaling_monotonic': bool(
          all(a < b for a, b in zip(curve, curve[1:]))),
      'serving_fleet_request_time_compiles': sum(
          r['request_time_compiles'] for r in runs.values()),
      'serving_fleet_scaleup_compiles': top['scaleup_compiles'],
      'fleet_scaleup_time_to_ready_s': round(
          max(top['scaleup_seconds'] or [0.0]), 4),
      'serving_fleet_swap_failed': top['failed'],
      'serving_fleet_swap_versions_served': top['versions_served'],
      'serving_fleet': {str(n): runs[n] for n in counts},
  }
  for n in counts:
    out['serving_fleet_actions_per_sec_r{}'.format(n)] = \
        runs[n]['actions_per_sec']
    out['serving_fleet_p99_ms_r{}'.format(n)] = runs[n]['p99_ms']
  return out


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument('--batch', type=int, default=8)
  parser.add_argument('--height', type=int, default=96)
  parser.add_argument('--width', type=int, default=128)
  parser.add_argument('--cem_samples', type=int, default=32)
  parser.add_argument('--cem_iters', type=int, default=2)
  parser.add_argument('--num_elites', type=int, default=8)
  parser.add_argument('--duration', type=float, default=3.0)
  parser.add_argument('--replica_counts', default='1,2,4')
  args = parser.parse_args(argv)
  out = run_bench(
      batch=args.batch, height=args.height, width=args.width,
      cem_samples=args.cem_samples, cem_iters=args.cem_iters,
      num_elites=args.num_elites, duration_s=args.duration,
      replica_counts=tuple(int(n) for n in
                           args.replica_counts.split(',')))
  print(json.dumps(out))
  return 0


if __name__ == '__main__':
  sys.exit(main())
