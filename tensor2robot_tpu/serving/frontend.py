"""Stdlib HTTP/JSON front door for a PolicyServer or a ServingFleet.

One thread per connection (``ThreadingHTTPServer``) feeding the shared
batcher — which is exactly the point: N concurrent HTTP callers coalesce
into megabatches behind one compiled program. JSON arrays are the wire
format (no external deps); the server's ``feature_spec`` casts them to
the executable's dtypes, so clients send plain nested lists.

The handler needs only ``submit(features) -> Future`` and ``stats()``,
so ``t2r_serve --replicas N`` mounts a :class:`~...fleet.ServingFleet`
(whose router front-ends the replica set) on the exact same door — a
ROUTER-level fleet-wide shed (:class:`RequestRejected` before any
replica queue is touched) answers the same 503 a single server's
admission control does, never a dropped connection (ISSUE 14
satellite, the PR 7/PR 10 frontend bug class).

Endpoints:
  * ``POST /v1/select_action`` — body ``{"features": {name: value}}``;
    200 -> ``{"outputs": {...}, "version": int, "latency_ms": float}``;
    400 on malformed/spec-violating requests, 503 when admission control
    (or the fleet router) sheds the request (retry against another
    replica/fleet), 500 on a failed batch.
  * ``GET /healthz`` — cumulative ``stats()`` as JSON (the fleet's
    version includes per-replica + ejection/scale totals).
  * ``GET /metricz`` — the registry's ``serving/`` + ``serving_fleet/``
    + ``inference/`` scalars (flat tag -> value JSON).
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Tuple

import numpy as np

from tensor2robot_tpu.observability import get_registry
from tensor2robot_tpu.serving.admission import RequestRejected
from tensor2robot_tpu.serving.server import PolicyServer

__all__ = ['build_http_server']


def _jsonable(value):
  if isinstance(value, np.ndarray):
    return value.tolist()
  if isinstance(value, (np.generic,)):
    return value.item()
  return value


class _Handler(BaseHTTPRequestHandler):
  # Set by build_http_server on the subclass. Duck-typed: a
  # PolicyServer or anything else exposing submit()/stats() (the
  # ServingFleet / FleetRouter front the same door).
  policy_server = None  # type: PolicyServer
  request_timeout_s: float = 60.0

  def log_message(self, *args) -> None:  # quiet: telemetry is the log
    pass

  def _reply(self, status: int, payload: dict) -> None:
    body = json.dumps(payload).encode('utf-8')
    self.send_response(status)
    self.send_header('Content-Type', 'application/json')
    self.send_header('Content-Length', str(len(body)))
    self.end_headers()
    self.wfile.write(body)

  def do_GET(self) -> None:  # noqa: N802 — http.server API
    if self.path == '/healthz':
      self._reply(200, {k: _jsonable(v)
                        for k, v in self.policy_server.stats().items()})
    elif self.path == '/metricz':
      scalars = get_registry().scalars()
      self._reply(200, {tag: value for tag, value in sorted(scalars.items())
                        if tag.startswith(('serving/', 'serving_fleet/',
                                           'inference/'))})
    else:
      self._reply(404, {'error': 'unknown path {}'.format(self.path)})

  def do_POST(self) -> None:  # noqa: N802 — http.server API
    if self.path != '/v1/select_action':
      self._reply(404, {'error': 'unknown path {}'.format(self.path)})
      return
    try:
      length = int(self.headers.get('Content-Length', 0))
      payload = json.loads(self.rfile.read(length) or b'{}')
      if not isinstance(payload, dict):
        raise ValueError('body must be a JSON object')
      features = payload['features']
      if not isinstance(features, dict):
        raise ValueError('"features" must be an object')
    except (ValueError, KeyError, TypeError) as e:
      self._reply(400, {'error': 'bad request: {}'.format(e)})
      return
    try:
      future = self.policy_server.submit(
          {name: np.asarray(value) for name, value in features.items()})
    except RequestRejected as e:
      self._reply(503, {'error': str(e)})
      return
    except RuntimeError as e:
      # Racing shutdown (batcher closed): still a clean "try elsewhere".
      self._reply(503, {'error': str(e)})
      return
    except ValueError as e:
      self._reply(400, {'error': str(e)})
      return
    try:
      result = future.result(timeout=self.request_timeout_s)
    except Exception as e:  # noqa: BLE001 — surface the batch failure
      self._reply(500, {'error': '{}: {}'.format(type(e).__name__, e)})
      return
    self._reply(200, {
        'outputs': {k: _jsonable(v) for k, v in result.outputs.items()},
        'version': result.version,
        'latency_ms': round(result.latency_ms, 3),
    })


def build_http_server(policy_server,
                      host: str = '127.0.0.1',
                      port: int = 0,
                      request_timeout_s: float = 60.0
                      ) -> Tuple[ThreadingHTTPServer, int]:
  """Binds the HTTP front end; returns ``(httpd, bound_port)``.

  ``policy_server`` is a :class:`PolicyServer` or a
  :class:`~tensor2robot_tpu.serving.fleet.ServingFleet` (anything with
  ``submit``/``stats``). ``port=0`` binds an ephemeral port (tests).
  Call ``httpd.serve_forever()`` (blocking) or drive it from a thread;
  ``httpd.shutdown()`` stops it — then close the server/fleet.
  """
  handler = type('PolicyHandler', (_Handler,), {
      'policy_server': policy_server,
      'request_timeout_s': request_timeout_s,
  })
  httpd = ThreadingHTTPServer((host, port), handler)
  return httpd, httpd.server_address[1]
