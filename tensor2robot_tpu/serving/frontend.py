"""Stdlib HTTP/JSON front door for a PolicyServer (``t2r_serve``).

One thread per connection (``ThreadingHTTPServer``) feeding the shared
batcher — which is exactly the point: N concurrent HTTP callers coalesce
into megabatches behind one compiled program. JSON arrays are the wire
format (no external deps); the server's ``feature_spec`` casts them to
the executable's dtypes, so clients send plain nested lists.

Endpoints:
  * ``POST /v1/select_action`` — body ``{"features": {name: value}}``;
    200 -> ``{"outputs": {...}, "version": int, "latency_ms": float}``;
    400 on malformed/spec-violating requests, 503 when admission control
    sheds the request (retry against another replica), 500 on a failed
    batch.
  * ``GET /healthz`` — cumulative :meth:`PolicyServer.stats` as JSON.
  * ``GET /metricz`` — the registry's ``serving/`` + ``inference/``
    scalars (flat tag -> value JSON).
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Tuple

import numpy as np

from tensor2robot_tpu.observability import get_registry
from tensor2robot_tpu.serving.admission import RequestRejected
from tensor2robot_tpu.serving.server import PolicyServer

__all__ = ['build_http_server']


def _jsonable(value):
  if isinstance(value, np.ndarray):
    return value.tolist()
  if isinstance(value, (np.generic,)):
    return value.item()
  return value


class _Handler(BaseHTTPRequestHandler):
  # Set by build_http_server on the subclass.
  policy_server: PolicyServer = None
  request_timeout_s: float = 60.0

  def log_message(self, *args) -> None:  # quiet: telemetry is the log
    pass

  def _reply(self, status: int, payload: dict) -> None:
    body = json.dumps(payload).encode('utf-8')
    self.send_response(status)
    self.send_header('Content-Type', 'application/json')
    self.send_header('Content-Length', str(len(body)))
    self.end_headers()
    self.wfile.write(body)

  def do_GET(self) -> None:  # noqa: N802 — http.server API
    if self.path == '/healthz':
      self._reply(200, {k: _jsonable(v)
                        for k, v in self.policy_server.stats().items()})
    elif self.path == '/metricz':
      scalars = get_registry().scalars()
      self._reply(200, {tag: value for tag, value in sorted(scalars.items())
                        if tag.startswith(('serving/', 'inference/'))})
    else:
      self._reply(404, {'error': 'unknown path {}'.format(self.path)})

  def do_POST(self) -> None:  # noqa: N802 — http.server API
    if self.path != '/v1/select_action':
      self._reply(404, {'error': 'unknown path {}'.format(self.path)})
      return
    try:
      length = int(self.headers.get('Content-Length', 0))
      payload = json.loads(self.rfile.read(length) or b'{}')
      if not isinstance(payload, dict):
        raise ValueError('body must be a JSON object')
      features = payload['features']
      if not isinstance(features, dict):
        raise ValueError('"features" must be an object')
    except (ValueError, KeyError, TypeError) as e:
      self._reply(400, {'error': 'bad request: {}'.format(e)})
      return
    try:
      future = self.policy_server.submit(
          {name: np.asarray(value) for name, value in features.items()})
    except RequestRejected as e:
      self._reply(503, {'error': str(e)})
      return
    except RuntimeError as e:
      # Racing shutdown (batcher closed): still a clean "try elsewhere".
      self._reply(503, {'error': str(e)})
      return
    except ValueError as e:
      self._reply(400, {'error': str(e)})
      return
    try:
      result = future.result(timeout=self.request_timeout_s)
    except Exception as e:  # noqa: BLE001 — surface the batch failure
      self._reply(500, {'error': '{}: {}'.format(type(e).__name__, e)})
      return
    self._reply(200, {
        'outputs': {k: _jsonable(v) for k, v in result.outputs.items()},
        'version': result.version,
        'latency_ms': round(result.latency_ms, 3),
    })


def build_http_server(policy_server: PolicyServer,
                      host: str = '127.0.0.1',
                      port: int = 0,
                      request_timeout_s: float = 60.0
                      ) -> Tuple[ThreadingHTTPServer, int]:
  """Binds the HTTP front end; returns ``(httpd, bound_port)``.

  ``port=0`` binds an ephemeral port (tests). Call
  ``httpd.serve_forever()`` (blocking) or drive it from a thread;
  ``httpd.shutdown()`` stops it — then close the PolicyServer.
  """
  handler = type('PolicyHandler', (_Handler,), {
      'policy_server': policy_server,
      'request_timeout_s': request_timeout_s,
  })
  httpd = ThreadingHTTPServer((host, port), handler)
  return httpd, httpd.server_address[1]
