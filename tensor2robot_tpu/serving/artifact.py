"""AOT serving executables: compile at startup, never at request time.

Since ISSUE 13 this module is a THIN ADAPTER over the unified
``tensor2robot_tpu/compile`` artifact pipeline (ROADMAP item 5 — this
file was its first slice, now generalized): the server's batch program
resolves through the same ``CompiledArtifact`` store the trainer, the
autotuner sweep, the RL acting step, and forensics use. What stays
serving-specific:

  * the tuning-cache WINNER resolution happens here (through the shared
    ``resolve_cache_winner`` guard — winners carrying model overrides
    or ``winner_ok=False`` placeholder entries are refused, never
    half-applied), so a re-swept cache whose winner moved forces one
    fresh startup compile under the new config instead of silently
    serving the old program;
  * the cache entry is stamped with the persisted executable's path
    (``'serialized_executable'``), keeping the tuning evidence and the
    program it picked in one place;
  * artifacts are keyed WITHOUT the lowered-program sha
    (``program_key=False``): serving workload names pin the program
    (``serving_qtopt_cem_b8``), and a warm restart must deserialize
    without paying even the trace.

The contract is unchanged: a warm restart deserializes and compiles
NOTHING; a cold start (or a stale/corrupt artifact) falls back to one
AOT compile and re-persists; either way there is nothing left to
compile when the first request arrives (``serving.request_time_compiles
== 0`` in the bench).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from tensor2robot_tpu.compile import artifact as artifact_lib
from tensor2robot_tpu.reliability.logutil import log_warning
from tensor2robot_tpu.tuning import cache as cache_lib

__all__ = ['ServingExecutable', 'artifact_path_for_key', 'load_or_compile',
           'ARTIFACT_SCHEMA', 'ARTIFACT_DIRNAME']

# The unified schema/dirname (kept exported: bin/t2r_serve and tests
# name them through this module).
ARTIFACT_SCHEMA = artifact_lib.ARTIFACT_SCHEMA
ARTIFACT_DIRNAME = artifact_lib.ARTIFACT_DIRNAME


@dataclasses.dataclass
class ServingExecutable:
  """One ready-to-call serving program + its provenance.

  ``from_cache`` True means the executable was DESERIALIZED (warm
  restart, zero XLA compiles this startup); False means one startup AOT
  compile happened. ``config_id`` names the tuning winner applied
  ('baseline' when the workload was never tuned or the winner carries
  model overrides the serving layer cannot re-apply).
  """

  executable: Any
  key: str
  workload: str
  config_id: str
  from_cache: bool
  path: str


def artifact_path_for_key(cache_path: str, key: str,
                          config_id: str = 'baseline') -> str:
  """Where the unified store keeps this key's executable — alongside
  the cache file, so one directory carries both the tuning evidence and
  the executable it picked."""
  return artifact_lib.ArtifactStore(cache_path).path_for(key, config_id)


def load_or_compile(workload: str,
                    jitted,
                    example_args,
                    cache: Optional[cache_lib.ConfigCache] = None,
                    cache_path: Optional[str] = None,
                    persist: bool = True,
                    telemetry: Optional[Any] = None) -> ServingExecutable:
  """The server-startup path: deserialize, else AOT-compile + persist.

  Args:
    workload: cache-key name, e.g. ``serving_qtopt_cem_b8``.
    jitted: the ``jax.jit`` object for the batch program.
    example_args: concrete or abstract (ShapeDtypeStruct) argument
      pytree — fixes the ONE shape the executable serves.
    cache / cache_path: the tuning cache holding this workload's winner;
      defaults to the process tuning cache.
    persist: serialize a freshly-compiled executable back to disk (and
      stamp its path into the cache entry when one exists).
    telemetry: optional TelemetryLogger for ``kind='compile'`` records.
  """
  import jax

  if cache is None:
    cache = cache_lib.ConfigCache(cache_path)
  device_kind = getattr(jax.devices()[0], 'device_kind', 'unknown')
  signature = cache_lib.abstract_signature(example_args)
  key = cache_lib.cache_key(workload, signature, device_kind)

  # Resolve the CURRENT winner first, through the shared guard: a
  # persisted executable is only valid under the config the cache names
  # today, and a winner the trainer would refuse (model overrides,
  # winner_ok=False) is refused here identically.
  entry = cache.lookup(key)
  winner, _ = artifact_lib.resolve_cache_winner(entry)

  artifact = artifact_lib.load_or_compile(
      workload, jitted, example_args, config=winner, cache=cache,
      persist=persist, program_key=False, telemetry=telemetry)
  if not artifact.from_cache and entry is not None:
    previous_config = entry.get('serialized_executable_config_id')
    if previous_config is not None and \
        previous_config != artifact.config_id:
      # The startup compile was caused by WINNER DRIFT, not a cold key:
      # a re-swept cache moved the winner, superseding the previously
      # stamped executable. Judged by the STAMPED config id — never by
      # path comparison, which a failed persist, a relocated cache dir,
      # or a path-scheme migration would each misfire. A surprise
      # multi-second warm-restart compile must be attributable from the
      # logs alone.
      log_warning(
          'Serving workload %r recompiled under config %r: the tuning '
          'cache winner moved (previously persisted under %r; '
          'superseded executable: %s).', workload, artifact.config_id,
          previous_config, entry.get('serialized_executable'))
    if artifact.path:
      # The cache entry gains a pointer to its executable (+ the config
      # it was built under) — the tuning evidence and the program it
      # picked stay joined.
      entry = dict(entry)
      entry['serialized_executable'] = artifact.path
      entry['serialized_executable_config_id'] = artifact.config_id
      cache.store(key, entry)
  return ServingExecutable(executable=artifact.executable,
                           key=artifact.key, workload=workload,
                           config_id=artifact.config_id,
                           from_cache=artifact.from_cache,
                           path=artifact.path)
