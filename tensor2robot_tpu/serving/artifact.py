"""AOT serving executables: compile at startup, never at request time.

The serving half of the ``CompiledArtifact`` story (ROADMAP item 5,
first slice): the server's batch program is lowered and compiled ONCE at
startup — under the ``tuning/`` cache winner's compiler options for this
exact workload+shapes+chip key, so the server runs the same config it
was tuned under — and the compiled executable is **serialized to disk
alongside the cache entry**. A warm restart deserializes it and skips
even the startup compile; a cold start (or a stale artifact: different
jax version, different chip, changed shapes) falls back to one AOT
compile and re-persists. Either way there is NOTHING left to compile by
the time the first request arrives, which the bench asserts via the
``jax/compiles`` counter (``serving.request_time_compiles == 0``).

Artifact files are atomic (tmp + rename), self-describing, and advisory:
any failure to load — corrupt pickle, jaxlib that cannot deserialize,
schema drift — degrades to the startup compile, never to a dead server.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import pickle
import tempfile
from typing import Any, Optional

from tensor2robot_tpu.reliability.logutil import log_warning
from tensor2robot_tpu.tuning import autotuner
from tensor2robot_tpu.tuning import cache as cache_lib
from tensor2robot_tpu.tuning import search_space

__all__ = ['ServingExecutable', 'artifact_path_for_key', 'load_or_compile',
           'ARTIFACT_SCHEMA', 'ARTIFACT_DIRNAME']

ARTIFACT_SCHEMA = 't2r.serving_artifact.v1'
ARTIFACT_DIRNAME = 'artifacts'


@dataclasses.dataclass
class ServingExecutable:
  """One ready-to-call serving program + its provenance.

  ``from_cache`` True means the executable was DESERIALIZED (warm
  restart, zero XLA compiles this startup); False means one startup AOT
  compile happened. ``config_id`` names the tuning winner applied
  ('baseline' when the workload was never tuned or the winner carries
  model overrides the serving layer cannot re-apply).
  """

  executable: Any
  key: str
  workload: str
  config_id: str
  from_cache: bool
  path: str


def artifact_path_for_key(cache_path: str, key: str) -> str:
  """``<cache dir>/artifacts/<sha1(key)>.pkl`` — alongside the cache
  file, so one directory carries both the tuning evidence and the
  executable it picked."""
  digest = hashlib.sha1(key.encode('utf-8')).hexdigest()[:20]
  return os.path.join(os.path.dirname(cache_path) or '.',
                      ARTIFACT_DIRNAME, digest + '.pkl')


def _winner_for_entry(entry) -> Optional[search_space.CompileConfig]:
  """The applicable tuning winner, or None (baseline compile).

  Mirrors the trainer's refusal to half-apply: a winner carrying
  ``model_overrides`` changed the MODEL the sweep measured; compiler
  options alone would attribute a config that never ran.
  """
  if not entry or not entry.get('winner_ok', True):
    return None
  try:
    winner = search_space.CompileConfig.from_dict(entry['winner'])
  except (KeyError, TypeError, ValueError):
    return None
  if winner.model_overrides:
    return None
  return winner


def _try_load(path: str, key: str, device_kind: str,
              expected_config_id: str):
  """Deserializes a persisted executable; None on any mismatch/corruption.

  ``expected_config_id`` is the CURRENT tuning-cache winner for this
  key: an artifact compiled under a different config is stale — a
  re-swept cache whose winner moved must trigger a fresh startup compile
  under the new winner, not silently keep serving the old program.
  """
  if not os.path.exists(path):
    return None
  try:
    with open(path, 'rb') as f:
      payload = pickle.load(f)
    if (payload.get('schema') != ARTIFACT_SCHEMA
        or payload.get('key') != key
        or payload.get('device_kind') != device_kind):
      return None
    if str(payload.get('config_id', 'baseline')) != expected_config_id:
      log_warning('Serving artifact %s was compiled under config %r but '
                  'the tuning cache now names %r; recompiling.', path,
                  payload.get('config_id'), expected_config_id)
      return None
    import jax
    from jax.experimental import serialize_executable

    if payload.get('jax_version') != jax.__version__:
      return None
    return serialize_executable.deserialize_and_load(
        payload['serialized'], payload['in_tree'], payload['out_tree'])
  except Exception as e:  # noqa: BLE001 — stale/corrupt artifact
    log_warning('Serving artifact %s failed to load (%s); falling back '
                'to a startup compile.', path, e)
    return None


def _persist(path: str, key: str, workload: str, device_kind: str,
             config_id: str, compiled) -> bool:
  """Serializes ``compiled`` to ``path`` atomically; False if the
  backend/executable does not support serialization."""
  try:
    from jax.experimental import serialize_executable
    import jax

    serialized, in_tree, out_tree = serialize_executable.serialize(compiled)
    payload = {
        'schema': ARTIFACT_SCHEMA,
        'key': key,
        'workload': workload,
        'device_kind': device_kind,
        'jax_version': jax.__version__,
        'config_id': config_id,
        'serialized': serialized,
        'in_tree': in_tree,
        'out_tree': out_tree,
    }
    directory = os.path.dirname(path) or '.'
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix='.tmp')
    try:
      with os.fdopen(fd, 'wb') as f:
        pickle.dump(payload, f)
      os.replace(tmp, path)
    finally:
      if os.path.exists(tmp):
        os.unlink(tmp)
    return True
  except Exception as e:  # noqa: BLE001 — e.g. backend without PJRT
    # serialization; the server still starts, it just cold-compiles.
    log_warning('Could not persist serving executable for %s: %s',
                workload, e)
    return False


def load_or_compile(workload: str,
                    jitted,
                    example_args,
                    cache: Optional[cache_lib.ConfigCache] = None,
                    cache_path: Optional[str] = None,
                    persist: bool = True) -> ServingExecutable:
  """The server-startup path: deserialize, else AOT-compile + persist.

  Args:
    workload: cache-key name, e.g. ``serving_qtopt_cem_b8``.
    jitted: the ``jax.jit`` object for the batch program.
    example_args: concrete or abstract (ShapeDtypeStruct) argument
      pytree — fixes the ONE shape the executable serves.
    cache / cache_path: the tuning cache holding this workload's winner;
      defaults to the process tuning cache.
    persist: serialize a freshly-compiled executable back to disk (and
      stamp its path into the cache entry when one exists).
  """
  import jax

  if cache is None:
    cache = cache_lib.ConfigCache(cache_path)
  device_kind = getattr(jax.devices()[0], 'device_kind', 'unknown')
  signature = cache_lib.abstract_signature(example_args)
  key = cache_lib.cache_key(workload, signature, device_kind)
  path = artifact_path_for_key(cache.path, key)

  # Resolve the CURRENT winner first: a persisted executable is only
  # valid if it was compiled under the config the cache names today.
  entry = cache.lookup(key)
  winner = _winner_for_entry(entry)
  config_id = winner.config_id if winner is not None else 'baseline'

  executable = _try_load(path, key, device_kind,
                         expected_config_id=config_id)
  if executable is not None:
    return ServingExecutable(executable=executable, key=key,
                             workload=workload, config_id=config_id,
                             from_cache=True, path=path)

  compiled = autotuner.compile_with_config(jitted, example_args, winner)
  persisted = persist and _persist(path, key, workload, device_kind,
                                   config_id, compiled)
  if persisted and entry is not None:
    # The cache entry gains a pointer to its executable — the first
    # slice of the unified CompiledArtifact (ROADMAP item 5).
    entry = dict(entry)
    entry['serialized_executable'] = path
    cache.store(key, entry)
  return ServingExecutable(executable=compiled, key=key, workload=workload,
                           config_id=config_id, from_cache=False,
                           path=path if persisted else '')
