"""Batched, AOT-compiled, SLO-tracked policy inference (ISSUE 8).

The serving subsystem the predictors feed: ``PolicyServer`` coalesces
concurrent ``SelectAction`` requests into padded megabatches
(`batcher.py`), sheds load when the queue saturates (`admission.py`),
executes through an executable that was AOT-compiled at startup from the
``tuning/`` cache winner — and persisted, so warm restarts skip even the
startup compile (`artifact.py`) — hot-swaps checkpoints via atomically
versioned parameter snapshots with zero dropped requests, and reports
per-request latency against an explicit SLO into the telemetry layer
(`server.py`; ``t2r_telemetry doctor`` + ``bin/check_serving_slo`` read
it back). ``bin/t2r_serve`` is the entry point; `frontend.py` is its
stdlib HTTP/JSON door. Contract + quickstart: docs/serving_contract.md.
"""

from tensor2robot_tpu.serving.admission import (
    AdmissionController,
    RequestRejected,
    SERVING_REJECTED_COUNTER,
)
from tensor2robot_tpu.serving.artifact import (
    ServingExecutable,
    artifact_path_for_key,
    load_or_compile,
)
from tensor2robot_tpu.serving.batcher import (
    DeadlineBatcher,
    PendingRequest,
    pad_batch,
    split_outputs,
)
from tensor2robot_tpu.serving.fleet import (
    SERVING_FLEET_BENCH_KEYS,
    SERVING_FLEET_RECORD_KIND,
    SERVING_FLEET_SCHEMA,
    ServingFleet,
    ServingFleetConfig,
    replica_host_meta,
    router_host_meta,
)
from tensor2robot_tpu.serving.router import (
    FleetRouter,
    HttpReplicaHandle,
    LocalReplicaHandle,
    ReplicaHandle,
    RoutedResult,
    RouterConfig,
)
from tensor2robot_tpu.serving.server import (
    PolicyServer,
    ServeResult,
    ServingConfig,
    SERVING_RECORD_KIND,
)

__all__ = [
    'AdmissionController',
    'DeadlineBatcher',
    'FleetRouter',
    'HttpReplicaHandle',
    'LocalReplicaHandle',
    'PendingRequest',
    'PolicyServer',
    'ReplicaHandle',
    'RequestRejected',
    'RoutedResult',
    'RouterConfig',
    'SERVING_FLEET_BENCH_KEYS',
    'SERVING_FLEET_RECORD_KIND',
    'SERVING_FLEET_SCHEMA',
    'SERVING_RECORD_KIND',
    'SERVING_REJECTED_COUNTER',
    'ServeResult',
    'ServingConfig',
    'ServingExecutable',
    'ServingFleet',
    'ServingFleetConfig',
    'artifact_path_for_key',
    'load_or_compile',
    'pad_batch',
    'split_outputs',
    'replica_host_meta',
    'router_host_meta',
]
