"""Back-compat shim: the batcher moved to ``serving.batching``.

The deadline batcher and padding helpers were extracted into the shared
import-light :mod:`tensor2robot_tpu.serving.batching` module (ISSUE 11
satellite) so the replay service's sampling front-end reuses them
without importing the policy server. Every historical name keeps
resolving from here.
"""

from tensor2robot_tpu.serving.batching import (
    DeadlineBatcher,
    PendingRequest,
    pad_batch,
    split_outputs,
)

__all__ = ['DeadlineBatcher', 'PendingRequest', 'pad_batch',
           'split_outputs']
