"""Admission control: shed load instead of growing an unbounded queue.

A serving SLO is a promise about the requests you ACCEPT. Once the
pending queue saturates, every additional admitted request makes every
queued request later — the p99 collapses for all callers instead of a
few callers getting a fast, explicit rejection they can retry against
another replica. ``AdmissionController`` is that tripwire: requests are
rejected while queue depth is at ``max_queue_depth``, and every shed
request is counted in ``serving/rejected`` so capacity planning sees
exactly how much demand was turned away (ISSUE 8).
"""

from __future__ import annotations

from typing import Optional

from tensor2robot_tpu.observability import get_registry

__all__ = ['AdmissionController', 'RequestRejected',
           'SERVING_REJECTED_COUNTER']

SERVING_REJECTED_COUNTER = 'serving/rejected'


class RequestRejected(RuntimeError):
  """The server is saturated; the caller should back off / retry
  elsewhere. Maps to HTTP 503 in the frontend."""


class AdmissionController:
  """Depth-based load shedding with rejection accounting."""

  def __init__(self, max_queue_depth: int, registry=None):
    if max_queue_depth < 1:
      raise ValueError('max_queue_depth must be >= 1; got {}.'.format(
          max_queue_depth))
    self.max_queue_depth = int(max_queue_depth)
    registry = registry or get_registry()
    self._rejected = registry.counter(SERVING_REJECTED_COUNTER)

  def admit(self, queue_depth: int) -> None:
    """Raises RequestRejected (and counts it) when the queue is full."""
    if queue_depth >= self.max_queue_depth:
      self._rejected.inc()
      raise RequestRejected(
          'serving queue saturated ({} pending >= max_queue_depth {}); '
          'request shed'.format(queue_depth, self.max_queue_depth))

  @property
  def rejected_total(self) -> float:
    return self._rejected.value
