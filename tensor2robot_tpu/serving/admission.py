"""Back-compat shim: admission control moved to ``serving.batching``.

Extracted into the shared import-light
:mod:`tensor2robot_tpu.serving.batching` module (ISSUE 11 satellite) so
the replay service's sampling front-end reuses the depth-based shedding
without importing the policy server. Every historical name keeps
resolving from here.
"""

from tensor2robot_tpu.serving.batching import (
    AdmissionController,
    RequestRejected,
    SERVING_REJECTED_COUNTER,
)

__all__ = ['AdmissionController', 'RequestRejected',
           'SERVING_REJECTED_COUNTER']
