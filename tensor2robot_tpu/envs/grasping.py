"""VecGraspingEnv: the numpy SimGraspingEnv as a pure-JAX batch of MDPs.

A per-slot parity port of ``research/qtopt/grasping_sim.SimGraspingEnv``
(tests/test_envs.py pins obs pixels, rewards, done/auto-reset semantics
and ``optimal_value`` agreement against the original), lifted to the
``envs.vec_env`` contract so the whole B-slot world advances inside one
jitted program:

  * **State is explicit**: ``GraspState(h, t, rng)`` with a leading
    ``num_envs`` dim; ``step`` is a pure function the actor fuses with
    CEM action selection (rl/loop.py) — the Anakin collect-on-device
    pattern (arXiv:2104.06272).
  * **Scenarios are a batch dimension**: every slot carries its own
    grasp threshold (object geometry), descent scale (dynamics), camera
    shift and sensor noise, sampled once from a seeded
    ``ScenarioConfig`` — one acting step sweeps ``num_envs`` DISTINCT
    scenarios, and each slot's difficulty ``bucket`` id keys the
    per-scenario success telemetry (``t2r.rl.v1``, docs/rl_loop.md).
  * **Replay semantics survive the port**: grasp attempts terminate
    with ``terminal=True``; timeouts end the episode (``done``) but are
    NOT env terminals — the loop writes them with ``done=0`` so value
    bootstraps through the time limit, exactly like the numpy
    collector path (grasping_sim module docstring).

Rendering reuses the numpy env's host-computed gradient background
(``grasping_sim.gradient_background``) and draws the object/gripper
blocks with index masks — the same float32 arithmetic as the numpy
slice assignments, so with matched noise the pixel parity is exact,
not approximate.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.envs.vec_env import VecEnv, VecStep
from tensor2robot_tpu.research.qtopt.grasping_sim import (
    CLOSE_INDEX,
    DESCENT_SCALE,
    GAMMA,
    H_MAX,
    THRESHOLD,
    WV_Z_INDEX,
    gradient_background,
)

__all__ = ['GraspState', 'ScenarioConfig', 'Scenarios', 'VecGraspingEnv',
           'sample_scenarios']


class GraspState(NamedTuple):
  """Per-slot env state; every leaf is [num_envs]-leading."""

  h: jnp.ndarray    # [B] float32 gripper height above the object
  t: jnp.ndarray    # [B] int32 step index within the episode
  rng: jnp.ndarray  # [B, 2] uint32 per-slot PRNG keys


@dataclasses.dataclass(frozen=True)
class ScenarioConfig:
  """Per-slot randomization ranges; the defaults reproduce the numpy
  env's fixed constants (no randomization — the parity configuration).

  ``randomized()`` is the scenario-sweep preset the loop/bench use: a
  spread of grasp thresholds (object geometry), descent scales
  (dynamics), small camera shifts and sensor-noise levels. Buckets
  partition ``threshold_range`` into ``num_buckets`` equal difficulty
  bins — the label per-scenario success telemetry aggregates by.
  """

  num_buckets: int = 8
  threshold_range: Tuple[float, float] = (THRESHOLD, THRESHOLD)
  descent_scale_range: Tuple[float, float] = (DESCENT_SCALE, DESCENT_SCALE)
  camera_shift_px: int = 0
  noise_scale_range: Tuple[float, float] = (4.0, 4.0)
  reset_h_range: Tuple[float, float] = (0.1, 1.1)

  @classmethod
  def randomized(cls, num_buckets: int = 8,
                 camera_shift_px: int = 2) -> 'ScenarioConfig':
    return cls(num_buckets=num_buckets,
               threshold_range=(0.35, 0.65),
               descent_scale_range=(0.25, 0.45),
               camera_shift_px=camera_shift_px,
               noise_scale_range=(0.0, 6.0))


class Scenarios(NamedTuple):
  """One sampled scenario per env slot (host numpy arrays)."""

  threshold: np.ndarray      # [B] float32
  descent_scale: np.ndarray  # [B] float32
  shift_y: np.ndarray        # [B] int32 camera shift (rows)
  shift_x: np.ndarray        # [B] int32 camera shift (cols)
  noise_scale: np.ndarray    # [B] float32 sensor noise stddev
  bucket: np.ndarray         # [B] int32 difficulty bucket id


def sample_scenarios(config: ScenarioConfig, num_envs: int,
                     seed: int = 0) -> Scenarios:
  """Draws ``num_envs`` scenarios from a seeded config, deterministically."""
  rng = np.random.RandomState(seed)
  lo, hi = config.threshold_range
  threshold = rng.uniform(lo, hi, num_envs).astype(np.float32)
  descent = rng.uniform(*config.descent_scale_range,
                        size=num_envs).astype(np.float32)
  shift = int(config.camera_shift_px)
  shift_y = rng.randint(-shift, shift + 1, num_envs).astype(np.int32)
  shift_x = rng.randint(-shift, shift + 1, num_envs).astype(np.int32)
  noise = rng.uniform(*config.noise_scale_range,
                      size=num_envs).astype(np.float32)
  if hi > lo:
    bucket = np.clip(((threshold - lo) / (hi - lo))
                     * config.num_buckets, 0,
                     config.num_buckets - 1).astype(np.int32)
  else:
    bucket = np.zeros(num_envs, np.int32)
  return Scenarios(threshold=threshold, descent_scale=descent,
                   shift_y=shift_y, shift_x=shift_x, noise_scale=noise,
                   bucket=bucket)


class _ScenarioSlot(NamedTuple):
  """The traced per-slot scenario leaves ``step``/``reset`` vmap over."""

  threshold: jnp.ndarray
  descent_scale: jnp.ndarray
  shift_y: jnp.ndarray
  shift_x: jnp.ndarray
  noise_scale: jnp.ndarray


class VecGraspingEnv(VecEnv):
  """B independent grasping MDPs, one jittable step (module docstring).

  Observations per slot match the numpy env (and the Grasping44 serving
  contract): ``image`` uint8 [H, W, 3], ``gripper_closed`` and
  ``height_to_bottom`` float32 scalars.
  """

  def __init__(self,
               num_envs: int,
               height: int = 64,
               width: int = 80,
               episode_length: int = 3,
               scenarios: Optional[Scenarios] = None,
               scenario_config: Optional[ScenarioConfig] = None,
               seed: int = 0,
               safe_region: Optional[Tuple[Tuple[int, int],
                                           Tuple[int, int]]] = None):
    if num_envs < 1:
      raise ValueError('num_envs must be >= 1; got {}'.format(num_envs))
    self._num_envs = int(num_envs)
    self._height = int(height)
    self._width = int(width)
    self._episode_length = int(episode_length)
    self.scenario_config = scenario_config or ScenarioConfig()
    if scenarios is None:
      scenarios = sample_scenarios(self.scenario_config, num_envs, seed)
    if len(scenarios.threshold) != num_envs:
      raise ValueError('scenarios carry {} slots for num_envs={}'.format(
          len(scenarios.threshold), num_envs))
    self.scenarios = scenarios
    if safe_region is None:
      # Same defaulting rule as SimGraspingEnv: the 512x640 camera frame
      # keeps scene content inside the crop-proof band.
      if (self._height, self._width) == (512, 640):
        safe_region = ((40, 472), (168, 472))
      else:
        safe_region = ((0, self._height), (0, self._width))
    self._safe = safe_region
    self._background = jnp.asarray(gradient_background(height, width))
    self._scn = _ScenarioSlot(
        threshold=jnp.asarray(scenarios.threshold),
        descent_scale=jnp.asarray(scenarios.descent_scale),
        shift_y=jnp.asarray(scenarios.shift_y),
        shift_x=jnp.asarray(scenarios.shift_x),
        noise_scale=jnp.asarray(scenarios.noise_scale))

  # -- properties ------------------------------------------------------------

  @property
  def num_envs(self) -> int:
    return self._num_envs

  @property
  def height(self) -> int:
    return self._height

  @property
  def width(self) -> int:
    return self._width

  @property
  def episode_length(self) -> int:
    return self._episode_length

  @property
  def buckets(self) -> np.ndarray:
    """Static per-slot difficulty bucket ids (host-side)."""
    return self.scenarios.bucket

  @property
  def num_buckets(self) -> int:
    return int(self.scenario_config.num_buckets)

  # -- rendering -------------------------------------------------------------

  def _render_one(self, h, scn: _ScenarioSlot):
    """One slot's pre-noise frame, float32 [H, W, 3].

    The same drawing the numpy env performs with slice assignment,
    expressed as index masks (jit/vmap-friendly); with zero camera
    shift the arithmetic is identical, which is what the pixel parity
    test relies on.
    """
    (y0, y1), (x0, x1) = self._safe
    band_h, band_w = y1 - y0, x1 - x0
    block = max(6, band_h // 14)
    cx = jnp.clip(x0 + band_w // 2 + scn.shift_x, x0 + block, x1 - block)
    obj_y = jnp.clip(y1 - 2 * block + scn.shift_y, y0, y1 - 2 * block)
    frac = jnp.clip(h / H_MAX, 0.0, 1.0)
    # int() truncation in the numpy env == floor here: the pre-clamp
    # value is >= y0 + block by construction (band geometry).
    grip_y = jnp.maximum(
        y0, jnp.floor(obj_y - block - frac * (band_h - 4 * block))
        .astype(jnp.int32))
    ys = jnp.arange(self._height)[:, None]
    xs = jnp.arange(self._width)[None, :]
    img = self._background
    obj = ((ys >= obj_y) & (ys < obj_y + block)
           & (xs >= cx - block) & (xs < cx + block))
    img = jnp.where(obj[..., None],
                    jnp.asarray((200.0, 40.0, 40.0), jnp.float32), img)
    grip = ((ys >= grip_y) & (ys < grip_y + block)
            & (xs >= cx - block // 2) & (xs < cx + block // 2))
    img = jnp.where(grip[..., None],
                    jnp.asarray((40.0, 200.0, 60.0), jnp.float32), img)
    return img

  def _finish_one(self, img, noise_scale, key):
    noise = jax.random.normal(
        key, (self._height, self._width, 1), jnp.float32)
    img = img + noise * noise_scale
    return jnp.clip(img, 0.0, 255.0).astype(jnp.uint8)

  def _obs_one(self, h, scn: _ScenarioSlot, key):
    image = self._finish_one(self._render_one(h, scn), scn.noise_scale,
                             key)
    return {'image': image,
            'gripper_closed': jnp.float32(0.0),
            'height_to_bottom': jnp.asarray(h, jnp.float32)}

  def render(self, h):
    """[B] heights -> uint8 frames under each slot's scenario, no noise
    (test/visualization helper; the step path uses the per-slot keys)."""
    def one(h_slot, scn):
      img = self._render_one(jnp.asarray(h_slot, jnp.float32), scn)
      return jnp.clip(img, 0.0, 255.0).astype(jnp.uint8)
    return jax.vmap(one)(jnp.asarray(h, jnp.float32), self._scn)

  # -- the contract ----------------------------------------------------------

  def state_for_heights(self, heights, rng) -> GraspState:
    """A fresh state pinned at explicit per-slot heights (parity tests)."""
    keys = jax.random.split(jnp.asarray(rng), self._num_envs)
    return GraspState(h=jnp.asarray(heights, jnp.float32),
                      t=jnp.zeros((self._num_envs,), jnp.int32),
                      rng=keys)

  def reset(self, rng):
    keys = jax.random.split(jnp.asarray(rng), self._num_envs)

    def one(key, scn):
      key, k_h, k_obs = jax.random.split(key, 3)
      lo, hi = self.scenario_config.reset_h_range
      h = jax.random.uniform(k_h, (), jnp.float32, lo, hi)
      return (h, jnp.int32(0), key), self._obs_one(h, scn, k_obs)

    (h, t, key), obs = jax.vmap(one)(keys, self._scn)
    return GraspState(h=h, t=t, rng=key), obs

  def step(self, state: GraspState, action) -> VecStep:
    """Advances every slot; auto-resets finished episodes (VecEnv)."""

    def one(h, t, key, scn, act):
      act = jnp.asarray(act, jnp.float32).reshape(-1)
      close = act[CLOSE_INDEX] > 0.5
      t1 = t + 1
      wv_z = jnp.clip(act[WV_Z_INDEX], -1.0, 1.0)
      h_moved = jnp.clip(h - scn.descent_scale * wv_z, 0.0, H_MAX)
      h_next = jnp.where(close, h, h_moved)
      terminal = close
      reward = jnp.where(close & (h <= scn.threshold), 1.0, 0.0)
      timeout = (~close) & (t1 >= self._episode_length)
      done = terminal | timeout
      key, k_next, k_obs, k_reset = jax.random.split(key, 4)
      next_obs = self._obs_one(h_next, scn, k_next)
      lo, hi = self.scenario_config.reset_h_range
      h_reset = jax.random.uniform(k_reset, (), jnp.float32, lo, hi)
      h_new = jnp.where(done, h_reset, h_next)
      t_new = jnp.where(done, jnp.int32(0), t1)
      reset_obs = self._obs_one(h_new, scn, k_obs)
      obs = jax.tree.map(
          lambda fresh, old: jnp.where(done, fresh, old), reset_obs,
          next_obs)
      return ((h_new, t_new, key), obs, reward, done,
              {'terminal': terminal, 'timeout': timeout,
               'next_obs': next_obs})

    (h, t, key), obs, reward, done, info = jax.vmap(one)(
        state.h, state.t, state.rng, self._scn, action)
    return VecStep(state=GraspState(h=h, t=t, rng=key), obs=obs,
                   reward=reward, done=done, info=info)

  # -- the analytic criterion ------------------------------------------------

  def steps_to_grasp(self, h):
    """Per-slot n(h) under each slot's threshold/descent (vectorized
    twin of grasping_sim.steps_to_grasp)."""
    h = jnp.asarray(h, jnp.float32)
    need = jnp.maximum(0.0, h - self._scn.threshold)
    return jnp.ceil(need / self._scn.descent_scale).astype(jnp.int32)

  def optimal_value(self, h, gamma: float = GAMMA):
    """V*(h) = gamma ** n(h) per slot (grasping_sim.optimal_value)."""
    return jnp.asarray(gamma, jnp.float32) ** self.steps_to_grasp(h)
