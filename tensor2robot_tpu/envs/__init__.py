"""Device-resident vectorized environments (ISSUE 12, the Anakin layer).

The Podracer/Anakin posture (arXiv:2104.06272): environments live ON
the accelerator as pure functions over explicit state, so one jitted
acting step advances thousands of env slots — and, because every
per-slot parameter is just a batch dimension, procedural scenario
randomization (object/threshold, camera, dynamics) is a *batch axis*,
not a config fork.

  * `vec_env.py` — the jittable environment contract: ``reset(rng) ->
    (state, obs)``, ``step(state, action) -> (state, obs, reward, done,
    info)`` with auto-reset, and the ``VecStep`` bookkeeping invariants
    the replay writer relies on (pre-reset ``next_obs``, the
    ``terminal`` vs ``done`` distinction for bootstrap-through-timeout).
  * `grasping.py` — ``VecGraspingEnv``: the pure-JAX port of the numpy
    ``SimGraspingEnv`` (research/qtopt/grasping_sim.py), per-slot
    parity-tested (tests/test_envs.py), with ``ScenarioConfig`` /
    ``sample_scenarios`` supplying per-slot threshold/dynamics/camera
    randomization and a difficulty bucket id per slot for the
    per-scenario success telemetry (docs/rl_loop.md).
"""

from tensor2robot_tpu.envs.grasping import (
    ScenarioConfig,
    Scenarios,
    VecGraspingEnv,
    sample_scenarios,
)
from tensor2robot_tpu.envs.vec_env import VecEnv, VecStep

__all__ = ['VecEnv', 'VecStep', 'VecGraspingEnv', 'ScenarioConfig',
           'Scenarios', 'sample_scenarios']
