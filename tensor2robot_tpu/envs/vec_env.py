"""The jittable vectorized environment contract.

A ``VecEnv`` is a *pure function pair* over explicit state — no hidden
mutation, no host round trips — so an actor can fuse "select action"
and "step every environment" into ONE jitted XLA program (rl/loop.py)
and sweep thousands of env slots per device step:

  * ``reset(rng) -> (state, obs)`` — ``state`` is a pytree whose leaves
    carry a leading ``num_envs`` dim; ``obs`` is a flat
    ``{name: array}`` dict, also batch-leading.
  * ``step(state, action) -> VecStep(state, obs, reward, done, info)``
    — advances EVERY slot one step and **auto-resets** finished slots:
    ``obs`` is what the policy should act on next (the fresh episode's
    first observation wherever ``done``), while ``info['next_obs']`` is
    the PRE-reset successor observation — the one a replay transition
    must record, because timeout transitions bootstrap through the time
    limit (``done=0`` on the wire) and therefore consume their true
    successor.

``done`` marks "this episode ended" (terminal OR timeout);
``info['terminal']`` marks "the environment itself terminated" (for the
grasping MDP: a grasp was attempted). Only ``terminal`` is written to
replay as ``done`` — the bootstrap-through-timeout convention of
research/qtopt/grasping_sim.py, carried into the vectorized world.

Both functions must be traceable (jit/vmap-safe) and totally
deterministic given ``(state, action)`` — all randomness flows through
per-slot PRNG keys carried IN the state, which is what makes the acting
step's jit cache hold exactly one executable per signature (the
zero-request-time-compile invariant the RL bench asserts).
"""

from __future__ import annotations

import abc
from typing import Any, Dict, NamedTuple, Tuple


class VecStep(NamedTuple):
  """One vectorized transition; every field is batch-leading.

  Attributes:
    state: the env state pytree AFTER auto-reset.
    obs: observation to act on next (post-auto-reset).
    reward: [B] float32 immediate rewards.
    done: [B] bool — episode ended this step (terminal or timeout).
    info: extras; the contract requires ``terminal`` ([B] bool, the
      env-terminal flag replay writes as ``done``) and ``next_obs``
      (the pre-reset successor observation dict).
  """

  state: Any
  obs: Dict[str, Any]
  reward: Any
  done: Any
  info: Dict[str, Any]


class VecEnv(abc.ABC):
  """Abstract jittable vectorized environment (module docstring)."""

  @property
  @abc.abstractmethod
  def num_envs(self) -> int:
    """B, the number of env slots advanced per step call."""

  @abc.abstractmethod
  def reset(self, rng) -> Tuple[Any, Dict[str, Any]]:
    """Fresh episodes in every slot; returns ``(state, obs)``."""

  @abc.abstractmethod
  def step(self, state, action) -> VecStep:
    """Advances every slot one step, auto-resetting finished ones."""
