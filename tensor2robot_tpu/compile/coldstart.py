"""Measured zero-compile cold start: the COLDSTART bench leg.

Builds the test-scale qtopt critic (the sim critic — a real
Grasping44-spec-keyed QT-Opt model), binds its train step through the
unified ``CompiledArtifact`` store, runs ONE completed (blocked) train
step, and reports:

  * ``time_to_first_step_s`` — wall time from trainer state-init to
    the first step's results being ready: checkpoint/state
    initialization, the artifact load-or-compile bind, and the first
    executed step — exactly the phase the artifact store addresses.
    Imports and model/generator construction happen BEFORE the clock
    starts: they are identical cold vs warm, and leaving ~4 s of
    constant import noise in the window would drown the compile
    savings of a test-scale model (at the 472x472 headline model the
    compile is tens of seconds and the distinction stops mattering);
  * ``step_compiles`` — the ``jax/compiles`` counter delta across
    artifact-bind + first step ONLY (eager-op warmup noise excluded by
    construction): the zero-compile cold-start contract as a number —
    0 on a warm store, > 0 on a cold one;
  * ``serving_time_to_ready_s`` — the serving adapter loading a
    batched CEM select program over the same critic (the
    ``serving/artifact.py`` path);
  * ``artifact_hits`` / ``artifact_misses`` — the store counters.

Run it as a SUBPROCESS for a true process cold start (bench.py does:
an in-process "warm" leg would also be warmed by jax's per-object and
eager caches, which is exactly the measurement error the subprocess
discipline exists to kill):

    python -m tensor2robot_tpu.compile.coldstart \
        --cache_path /tmp/store/tuning_cache.json --model_dir /tmp/run

Prints one JSON line on stdout. Also imported directly by
tests/test_compile_artifact.py — the in-process warm call still proves
the artifact path compiles nothing, because a fresh ``jax.jit`` object
never shares an executable cache with the first trainer's.
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Dict


def measure(cache_path: str, model_dir: str, batch_size: int = 8,
            height: int = 32, width: int = 40,
            serving_batch: int = 4, seed: int = 0,
            model_name: str = 'sim') -> Dict[str, object]:
  """One cold-start measurement; see the module docstring.

  ``model_name``: ``'sim'`` (the test-scale sim critic at
  height x width — what the test suite uses) or ``'grasping44'`` (the
  REAL flagship 19-layer QT-Opt critic at camera resolution — what the
  bench uses: its multi-second step compile makes the cold-vs-warm
  delta unmistakable).
  """
  import jax
  import numpy as np
  import optax
  from jax.sharding import NamedSharding, PartitionSpec as P

  from tensor2robot_tpu.data.input_generators import (
      DefaultRandomInputGenerator,
  )
  from tensor2robot_tpu.modes import ModeKeys
  from tensor2robot_tpu.observability import get_registry
  from tensor2robot_tpu.observability import signals as signals_lib
  from tensor2robot_tpu.research.qtopt import grasping_sim
  from tensor2robot_tpu.rl.loop import make_cem_select_fn
  from tensor2robot_tpu.serving import artifact as serving_artifact
  from tensor2robot_tpu.trainer import Trainer
  from tensor2robot_tpu.trainer.train_eval import (
      provide_input_generator_with_model_information,
  )
  from tensor2robot_tpu.tuning import cache as cache_lib

  signals_lib.install_jax_listeners()
  registry = get_registry()

  if model_name == 'grasping44':
    from tensor2robot_tpu.research.qtopt.t2r_models import (
        Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom,
    )

    model = Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom(
        device_type='cpu')
    height, width = 512, 640  # the flagship camera frame
    workload = 'coldstart_qtopt44_b{}'.format(batch_size)
  elif model_name == 'sim':
    model = grasping_sim.make_sim_critic_model(
        height, width, create_optimizer_fn=lambda: optax.adam(3e-3))
    workload = 'coldstart_qtopt_b{}'.format(batch_size)
  else:
    raise ValueError('model_name must be "sim" or "grasping44"; got '
                     '{!r}.'.format(model_name))
  generator = DefaultRandomInputGenerator(batch_size=batch_size)
  trainer = Trainer(model, model_dir, async_checkpoints=False,
                    save_checkpoints_steps=10**9,
                    log_every_n_steps=10**9, auto_profile=False,
                    enable_watchdog=False, enable_pipeline_xray=False,
                    write_metrics=False, use_compiled_artifacts=True,
                    artifact_workload=workload,
                    tuning_cache_path=cache_path, seed=seed)
  try:
    generator = provide_input_generator_with_model_information(
        generator, model, ModeKeys.TRAIN)
    iterator = generator.create_dataset_iterator(mode=ModeKeys.TRAIN,
                                                 seed=seed)
    features, labels = next(iterator)
    t_start = time.perf_counter()
    state = trainer.init_state(features, labels)
    step_fn = trainer._compile_train_step()  # noqa: SLF001 — the bench
    # measures the exact first-call bind path the train loop drives.
    device_batch = trainer._put_batch(  # noqa: SLF001
        {'features': features.to_dict(), 'labels': labels.to_dict()})
    base_rng = jax.device_put(jax.random.PRNGKey(seed + 1),
                              NamedSharding(trainer.mesh, P()))

    # The contract window: artifact bind + first executed step. Eager
    # warmup compiles (PRNG seeding, host preprocessing) happened above
    # and are identical cold vs warm — they are process startup, not
    # the step compile this axis measures.
    compiles_before = registry.counter(signals_lib.COMPILE_COUNTER).value
    state, metrics = step_fn(state, device_batch['features'],
                             device_batch['labels'], base_rng)
    jax.block_until_ready(metrics)
    time_to_first_step = time.perf_counter() - t_start
    step_compiles = (registry.counter(signals_lib.COMPILE_COUNTER).value
                     - compiles_before)

    # Serving leg: the batched CEM select program over the same critic
    # through the serving adapter (program pinned by the workload name).
    variables = {'params': state.params}
    if state.model_state:
      variables.update(state.model_state)
    select = make_cem_select_fn(model, cem_samples=4, cem_iters=1,
                                num_elites=2)
    batched = jax.jit(jax.vmap(select, in_axes=(None, 0, 0)))
    obs = {
        'image': np.zeros((serving_batch, height, width, 3), np.uint8),
        'gripper_closed': np.zeros((serving_batch,), np.float32),
        'height_to_bottom': np.full((serving_batch,), 10.0, np.float32),
    }
    keys = jax.random.split(jax.random.PRNGKey(seed), serving_batch)
    t0 = time.perf_counter()
    served = serving_artifact.load_or_compile(
        'coldstart_serving_{}_b{}'.format(model_name, serving_batch),
        batched, (variables, obs, keys),
        cache=cache_lib.ConfigCache(cache_path))
    jax.block_until_ready(served.executable(variables, obs, keys))
    serving_time_to_ready = time.perf_counter() - t0

    scalars = registry.scalars()
    hits = sum(value for tag, value in scalars.items()
               if tag.startswith('compile/artifact_hits'))
    misses = sum(value for tag, value in scalars.items()
                 if tag.startswith('compile/artifact_misses'))
    return {
        'time_to_first_step_s': round(time_to_first_step, 3),
        'step_compiles': int(step_compiles),
        'serving_time_to_ready_s': round(serving_time_to_ready, 3),
        'serving_from_cache': bool(served.from_cache),
        'trainer_from_cache': bool(
            trainer._train_step_artifact is not None  # noqa: SLF001
            and trainer._train_step_artifact.from_cache),  # noqa: SLF001
        'artifact_hits': int(hits),
        'artifact_misses': int(misses),
    }
  finally:
    trainer.close()


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument('--cache_path', required=True,
                      help='tuning-cache path; artifacts persist beside it')
  parser.add_argument('--model_dir', required=True)
  parser.add_argument('--batch_size', type=int, default=8)
  parser.add_argument('--height', type=int, default=32)
  parser.add_argument('--width', type=int, default=40)
  parser.add_argument('--seed', type=int, default=0)
  parser.add_argument('--model', default='sim',
                      choices=('sim', 'grasping44'),
                      help='trainer model: test-scale sim critic or the '
                           'flagship 19-layer QT-Opt critic (bench).')
  args = parser.parse_args(argv)
  result = measure(args.cache_path, args.model_dir,
                   batch_size=args.batch_size, height=args.height,
                   width=args.width, seed=args.seed,
                   model_name=args.model)
  print(json.dumps(result))
  return 0


if __name__ == '__main__':
  import sys

  sys.exit(main())
