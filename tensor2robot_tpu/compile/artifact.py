"""One ``CompiledArtifact`` pipeline: persisted executables for every
compile site (ROADMAP item 5).

Five subsystems independently lower/compile/fingerprint the same step
functions — trainer jit, the autotuner AOT sweep, serving startup,
the RL acting step, and forensics' HLO relowering — and every process
pays the same multi-second XLA compile on every cold start. This module
is the one abstraction they all resolve through:

  * **CompiledArtifact** — a ready-to-call executable plus its full
    provenance: the lowered (StableHLO) program hash, the compiler
    options it was built under, in/out layouts, the post-optimization
    HLO text + fingerprint, and the
    ``jax.experimental.serialize_executable`` payload.
  * **ArtifactStore** — an atomic (tmp + rename) on-disk store living
    next to the tuning cache (``<cache dir>/artifacts/``), keyed like
    the tuning cache — ``workload | device_kind | jax-version |
    shapes-sha`` — extended with the candidate ``config_id`` and (for
    program-keyed callers) the lowered-program sha, so two different
    models sharing argument shapes can NEVER load each other's
    executable.
  * **load_or_compile** — the one cold-start path: deserialize the
    persisted executable when the key matches (zero backend compiles —
    deserialization fires no ``jax/compiles`` events, measured), else
    one AOT compile that is persisted for next time. A miss, a stale
    payload (jax upgrade, different chip), or a corrupt file each
    degrade to the stock compile — never to a dead process.

**Fingerprint drift** is the first-class signal this unification buys:
when the store holds a readable payload for the exact key being compiled
and the fresh program's post-optimization fingerprint differs, the same
(workload, shapes, chip, jax version, config) tuple no longer lowers to
the same program — a toolchain moved underneath a pinned version string,
or lowering went nondeterministic. That is a
``compile/fingerprint_drift`` counter increment, one ``anomaly``
telemetry record naming the workload, and a doctor WARNING/CRITICAL —
instead of something the watchdog infers from a recompile gauge after
the fact.

Import-light by contract: jax is imported inside functions only, so the
jax-free readers (doctor, ``bin/check_artifact_doctor``) can import the
schema/key vocabulary below.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import pickle
import tempfile
import threading
import time
from typing import Any, Dict, Optional, Tuple

from tensor2robot_tpu.observability import registry as registry_lib
from tensor2robot_tpu.reliability.logutil import log_warning

__all__ = [
    'ARTIFACT_SCHEMA', 'ARTIFACT_DIRNAME', 'COMPILE_RECORD_KIND',
    'FINGERPRINT_DRIFT', 'ARTIFACT_HITS_COUNTER', 'ARTIFACT_MISSES_COUNTER',
    'DRIFT_COUNTER', 'COLDSTART_BENCH_KEYS', 'CompiledArtifact',
    'ArtifactStore', 'artifact_key', 'program_sha', 'compile_lowered',
    'resolve_cache_winner', 'load_or_compile',
]

ARTIFACT_SCHEMA = 't2r.compiled_artifact.v1'
ARTIFACT_DIRNAME = 'artifacts'

# Telemetry vocabulary (jax-free — doctor/CLI/CI gates import these).
COMPILE_RECORD_KIND = 'compile'
FINGERPRINT_DRIFT = 'fingerprint_drift'
ARTIFACT_HITS_COUNTER = 'compile/artifact_hits'
ARTIFACT_MISSES_COUNTER = 'compile/artifact_misses'
DRIFT_COUNTER = 'compile/fingerprint_drift'

# The bench's cold-start axis (schema-locked by bin/check_artifact_doctor
# exactly like the E2E/REPLAY/RL key tuples): cold vs warm
# time-to-first-step for the qtopt trainer measured in SUBPROCESSES
# (a true process cold start, not a warm in-process jit cache), the
# warm leg's backend-compile count around its first step (MUST be 0 —
# the zero-compile cold-start contract as a number), serving
# time-to-ready on a warm store, and the store's hit/miss counts.
COLDSTART_BENCH_KEYS = (
    'coldstart_time_to_first_step_s_cold',
    'coldstart_time_to_first_step_s_warm',
    'coldstart_warm_vs_cold',
    'coldstart_warm_compiles',
    'coldstart_serving_time_to_ready_warm_s',
    'coldstart_artifact_hits',
    'coldstart_artifact_misses',
)


@dataclasses.dataclass
class CompiledArtifact:
  """One ready-to-call executable + the provenance it was built under.

  ``from_cache`` True means the executable was DESERIALIZED from the
  store (zero backend compiles this load); False means one AOT compile
  happened (and was persisted when ``persist``). ``hlo_text`` is the
  POST-OPTIMIZATION compiled HLO — what forensics' collective analysis
  consumes, so a capture can be attributed without relowering (one
  extra XLA compile) or calling into a deserialized executable.
  """

  executable: Any
  key: str
  workload: str
  config_id: str
  from_cache: bool
  path: str
  fingerprint: str = ''
  hlo_text: Optional[str] = None
  compiler_options: Optional[Dict[str, Any]] = None
  compile_s: float = 0.0
  outcome: str = 'compiled'
  drift: bool = False


def program_sha(lowered_text: str) -> str:
  """Short stable sha of a LOWERED (StableHLO) program.

  The program-identity component of the artifact key: two different
  models whose step arguments share shapes lower to different programs,
  and this hash is what keeps their artifacts from colliding. Lowering
  is a trace, not an XLA compile — it fires no ``jax/compiles`` events,
  so program-keyed cold starts stay zero-compile.
  """
  return hashlib.sha1(lowered_text.encode('utf-8')).hexdigest()[:16]


def artifact_key(workload: str, signature: str, device_kind: str,
                 jax_version: Optional[str] = None,
                 lowered_sha: Optional[str] = None) -> str:
  """``workload|device_kind|jax-<v>|<shapes-sha>[|hlo-<sha>]`` — the
  tuning-cache key tuple, optionally extended with the lowered-program
  hash for callers whose workload name alone does not pin the program."""
  from tensor2robot_tpu.tuning import cache as cache_lib

  key = cache_lib.cache_key(workload, signature, device_kind,
                            jax_version=jax_version)
  if lowered_sha:
    key += '|hlo-' + lowered_sha
  return key


def compile_lowered(lowered, options: Optional[Dict[str, Any]] = None):
  """The ONE place compiler options meet ``lowered.compile``.

  Every consumer that already holds a ``lowered`` object — this
  module's ``load_or_compile``, the autotuner sweep, and the legacy
  trainer hook via ``autotuner.compile_with_config`` — compiles through
  here, so a change to HOW options are applied cannot silently diverge
  the sweep's measured candidates from the executables later loaded by
  key.
  """
  options = dict(options or {})
  if options:
    return lowered.compile(compiler_options=options)
  return lowered.compile()


def resolve_cache_winner(entry) -> Tuple[Optional[Any], str]:
  """The ONE stale-winner guard for every artifact consumer.

  ``entry`` is a tuning-cache entry (or None). Returns
  ``(config, reason)`` where ``config`` is the applicable
  ``CompileConfig`` or None (baseline compile) and ``reason`` names why:

    * ``'no_entry'`` — never tuned (cache miss);
    * ``'winner_ok_false'`` — the sweep measured NOTHING (every
      candidate failed to compile); the stored config is a placeholder,
      not a winner;
    * ``'model_overrides'`` — the measured winner included layout
      overrides, which apply only at model construction; compiling just
      its flags here would run an unmeasured hybrid attributed to a
      config that never ran (the trainer's PR-5 refusal, now shared);
    * ``'invalid_winner'`` — the stored winner dict does not parse;
    * ``'ok'`` — ``config`` is applicable as-is.

  Both the trainer's cache hook and the serving/artifact load path call
  this, so the half-apply rules can never drift apart again.
  """
  from tensor2robot_tpu.tuning import search_space

  if not entry:
    return None, 'no_entry'
  if not entry.get('winner_ok', True):
    return None, 'winner_ok_false'
  try:
    winner = search_space.CompileConfig.from_dict(entry['winner'])
  except (KeyError, TypeError, ValueError):
    return None, 'invalid_winner'
  if winner.model_overrides:
    return None, 'model_overrides'
  return winner, 'ok'


def _layout_text(compiled, attr: str) -> Optional[str]:
  try:
    return str(getattr(compiled, attr))
  except Exception:  # noqa: BLE001 — layouts are provenance, not contract
    return None


class ArtifactStore:
  """Atomic on-disk store of serialized executables next to the cache.

  One directory (``<cache dir>/artifacts/``) carries the tuning
  evidence AND every executable compiled under it. Files are one pickle
  per (key, config_id) pair, written tmp + rename so two processes
  racing ``load_or_compile`` on the same key produce one valid file and
  never a torn one (the tuning-cache discipline).

  The store is SIZE-CAPPED (``max_bytes``, default 4 GiB — the same
  bounded-on-disk discipline as telemetry rotation): superseded
  artifacts — old jax versions, re-swept candidates whose winner moved,
  changed shapes — are keyed to paths nothing loads anymore, so
  without a cap a long-lived dev/CI machine accumulates orphaned
  multi-MB executables forever. Each persist prunes oldest-first by
  mtime past the cap, and each HIT touches its file, so mtime is a
  live LRU signal and an actively-loaded artifact outlives dead ones.
  """

  def __init__(self, cache_path: Optional[str] = None,
               max_bytes: int = 4 * 2**30):
    if cache_path is None:
      from tensor2robot_tpu.tuning import cache as cache_lib

      cache_path = cache_lib.default_cache_path()
    self.cache_path = cache_path
    self.max_bytes = int(max_bytes)
    self.directory = os.path.join(os.path.dirname(cache_path) or '.',
                                  ARTIFACT_DIRNAME)

  def _prune(self, keep_path: str) -> None:
    """Evicts oldest-mtime artifacts until the store fits max_bytes.

    ``keep_path`` (the file just written) is never evicted — a single
    artifact larger than the whole cap must still persist. Best-effort:
    a racing process deleting the same file is fine.
    """
    try:
      entries = []
      for name in os.listdir(self.directory):
        if not name.endswith('.pkl'):
          continue
        path = os.path.join(self.directory, name)
        try:
          stat = os.stat(path)
        except OSError:
          continue
        entries.append((stat.st_mtime, stat.st_size, path))
      total = sum(size for _, size, _ in entries)
      if total <= self.max_bytes:
        return
      for _, size, path in sorted(entries):
        if path == keep_path:
          continue
        try:
          os.unlink(path)
        except OSError:
          continue
        total -= size
        if total <= self.max_bytes:
          return
    except OSError:  # noqa: PERF203 — directory vanished mid-walk
      pass

  def path_for(self, key: str, config_id: str = 'baseline') -> str:
    digest = hashlib.sha1('{}|{}'.format(key, config_id).encode(
        'utf-8')).hexdigest()[:20]
    return os.path.join(self.directory, digest + '.pkl')

  def read_payload(self, path: str) -> Optional[Dict[str, Any]]:
    """The raw payload dict, or None on missing/corrupt/foreign files."""
    if not os.path.exists(path):
      return None
    try:
      with open(path, 'rb') as f:
        payload = pickle.load(f)
      if not isinstance(payload, dict) or \
          payload.get('schema') != ARTIFACT_SCHEMA:
        return None
      return payload
    except Exception as e:  # noqa: BLE001 — torn/corrupt artifact
      log_warning('Artifact %s unreadable (%s); treating as a miss.',
                  path, e)
      return None

  def persist(self, workload: str, key: str, config_id: str,
              compiler_options: Optional[Dict[str, Any]],
              compiled, lowered_sha: Optional[str] = None,
              fingerprint: Optional[str] = None,
              hlo_text: Optional[str] = None) -> str:
    """Serializes one compiled executable; '' when the backend cannot.

    Best-effort by contract (a backend without PJRT serialization still
    trains/serves, it just cold-compiles next time). The payload is
    self-describing: everything ``load`` validates rides inside it.
    """
    try:
      import jax
      from jax.experimental import serialize_executable

      if hlo_text is None:
        try:
          hlo_text = compiled.as_text()
        except Exception:  # noqa: BLE001 — text is evidence, not contract
          hlo_text = None
      if fingerprint is None and hlo_text:
        from tensor2robot_tpu.parallel import hlo_analysis

        fingerprint = hlo_analysis.program_fingerprint(hlo_text)
      serialized, in_tree, out_tree = \
          serialize_executable.serialize(compiled)
      payload = {
          'schema': ARTIFACT_SCHEMA,
          'key': key,
          'workload': workload,
          'config_id': config_id,
          'compiler_options': dict(compiler_options or {}),
          'device_kind': getattr(jax.devices()[0], 'device_kind',
                                 'unknown'),
          'jax_version': jax.__version__,
          'lowered_sha': lowered_sha,
          'fingerprint': fingerprint or '',
          'hlo_text': hlo_text,
          'in_layouts': _layout_text(compiled, 'input_layouts'),
          'out_layouts': _layout_text(compiled, 'output_layouts'),
          'serialized': serialized,
          'in_tree': in_tree,
          'out_tree': out_tree,
      }
      path = self.path_for(key, config_id)
      os.makedirs(self.directory, exist_ok=True)
      fd, tmp = tempfile.mkstemp(dir=self.directory, suffix='.tmp')
      try:
        with os.fdopen(fd, 'wb') as f:
          pickle.dump(payload, f)
        os.replace(tmp, path)
      finally:
        if os.path.exists(tmp):
          os.unlink(tmp)
      self._prune(keep_path=path)
      return path
    except Exception as e:  # noqa: BLE001 — e.g. backend without PJRT
      log_warning('Could not persist compiled artifact for %s: %s',
                  workload, e)
      return ''

  def load(self, key: str, config_id: str = 'baseline'
           ) -> Tuple[Optional[Any], Optional[Dict[str, Any]], str]:
    """``(executable, payload, reason)`` for one key.

    ``executable`` is the deserialized ready-to-call program or None;
    ``payload`` is the readable payload even when deserialization
    failed (the drift-detection evidence: its ``fingerprint`` is what
    the fresh compile is compared against); ``reason`` one of
    ``'hit' | 'miss' | 'stale' | 'exec_load_failed'``.
    """
    path = self.path_for(key, config_id)
    payload = self.read_payload(path)
    if payload is None:
      return None, None, 'miss'
    import jax

    device_kind = getattr(jax.devices()[0], 'device_kind', 'unknown')
    if (payload.get('key') != key
        or payload.get('config_id') != config_id
        or payload.get('device_kind') != device_kind
        or payload.get('jax_version') != jax.__version__):
      # The key embeds device/jax already; these field checks catch a
      # tampered or hash-collided payload — stale, recompile.
      return None, payload, 'stale'
    try:
      from jax.experimental import serialize_executable

      executable = serialize_executable.deserialize_and_load(
          payload['serialized'], payload['in_tree'], payload['out_tree'])
      try:
        os.utime(path)  # LRU touch: a loaded artifact outlives dead ones
      except OSError:
        pass
      return executable, payload, 'hit'
    except Exception as e:  # noqa: BLE001 — jaxlib that cannot load it
      log_warning('Artifact %s failed to deserialize (%s); recompiling.',
                  path, e)
      return None, payload, 'exec_load_failed'


def _record_compile(telemetry, registry, workload: str, key: str,
                    config_id: str, outcome: str, reason: str,
                    compile_s: float, fingerprint: str, drift: bool,
                    path: str) -> None:
  """Counters always; one ``kind='compile'`` record (+ one ``anomaly``
  on drift) when a telemetry logger rides along."""
  counter = (ARTIFACT_HITS_COUNTER if outcome == 'hit'
             else ARTIFACT_MISSES_COUNTER)
  registry.counter_family(counter, ('workload',)).series(workload).inc()
  if drift:
    registry.counter(DRIFT_COUNTER).inc()
  if telemetry is None:
    return
  try:
    telemetry.log(COMPILE_RECORD_KIND, workload=workload, key=key,
                  config_id=config_id, outcome=outcome, reason=reason,
                  compile_ms=round(compile_s * 1e3, 2),
                  fingerprint=fingerprint, drift=drift, path=path)
    if drift:
      telemetry.log(
          'anomaly', anomaly=FINGERPRINT_DRIFT,
          message='compiled-program fingerprint drifted for workload '
                  '{!r}: same artifact key, different post-optimization '
                  'HLO'.format(workload),
          detail={'workload': workload, 'key': key,
                  'config_id': config_id})
    telemetry.flush()
  except Exception as e:  # noqa: BLE001 — telemetry must not kill a load
    log_warning('compile telemetry record failed: %s', e)


# In-process executable memo: one LOADED executable per artifact file,
# shared by every later load_or_compile of the same key in this process.
# An elastic rebuild at the same world shape (elastic/driver.py builds
# a fresh Trainer per plan epoch) should not re-deserialize a program
# object this process already holds. NOTE the memo only skips the
# DESERIALIZATION: with program_key=True (the trainer default) the key
# itself needs the lowered-program sha, so each load still pays one
# trace before the memo is consulted — a rebind is trace + lookup, not
# a pure dictionary hit. An entry is valid only while its backing FILE is
# the one it was loaded from: every (re-)persist lands via tmp +
# os.replace, which changes the inode, so the (st_ino, st_size) stamp
# detects a re-persist by any process (winner moved, drift) while
# staying immune to the LRU utime touches concurrent hitters apply to a
# live file.
_MEMO_LOCK = threading.Lock()
_LOADED_MEMO: Dict[str, Tuple[Optional[Tuple[int, int]],
                              'CompiledArtifact']] = {}


def _file_stamp(path: str) -> Optional[Tuple[int, int]]:
  try:
    stat = os.stat(path)
    return (stat.st_ino, stat.st_size)
  except OSError:
    return None


def _memo_get(path: str) -> Optional['CompiledArtifact']:
  with _MEMO_LOCK:
    entry = _LOADED_MEMO.get(path)
  if entry is None:
    return None
  stamp, artifact = entry
  if stamp is not None and _file_stamp(path) != stamp:
    with _MEMO_LOCK:
      _LOADED_MEMO.pop(path, None)
    return None
  return artifact


def _memo_put(path: str, artifact: 'CompiledArtifact') -> None:
  if not path:
    return  # never persisted: nothing another process could move
  with _MEMO_LOCK:
    _LOADED_MEMO[path] = (_file_stamp(path), artifact)


def load_or_compile(workload: str,
                    jitted,
                    example_args,
                    config: Optional[Any] = None,
                    cache: Optional[Any] = None,
                    cache_path: Optional[str] = None,
                    store: Optional[ArtifactStore] = None,
                    persist: bool = True,
                    program_key: bool = True,
                    telemetry: Optional[Any] = None,
                    registry: Optional[Any] = None) -> CompiledArtifact:
  """The one cold-start path every compile site resolves through.

  Args:
    workload: artifact-key name (``'qtopt_critic_b512'``,
      ``'serving_qtopt_cem_b8'``, ``'rl_act_16'`` ...).
    jitted: the ``jax.jit`` object for the step (shardings/donation
      already applied by the caller).
    example_args: concrete or abstract (ShapeDtypeStruct) argument
      pytree — fixes the ONE shape the executable serves.
    config: an applicable tuning ``CompileConfig`` (pass the result of
      :func:`resolve_cache_winner` for cache-resolved winners — the
      shared guard has already refused half-applicable ones) or None
      for the baseline compile.
    cache / cache_path / store: where artifacts persist; defaults to
      the process tuning cache's directory.
    persist: serialize a freshly-compiled executable back to the store.
    program_key: include the lowered-program sha in the key. Costs one
      trace (never an XLA compile) per load and makes the key collision
      -proof across models sharing shapes — the default for the trainer
      and the RL acting step. Serving passes False: its workload names
      pin the program and its warm restart must not pay the trace.
    telemetry: optional TelemetryLogger for ``kind='compile'`` records
      (and the ``fingerprint_drift`` anomaly record).
  """
  import jax

  registry = registry or registry_lib.get_registry()
  if store is None:
    if cache is not None:
      store = ArtifactStore(cache.path)
    else:
      store = ArtifactStore(cache_path)
  from tensor2robot_tpu.tuning import cache as cache_lib

  device_kind = getattr(jax.devices()[0], 'device_kind', 'unknown')
  signature = cache_lib.abstract_signature(example_args)
  lowered = None
  lowered_sha = None
  if program_key:
    lowered = jitted.lower(*example_args)
    lowered_sha = program_sha(lowered.as_text())
  key = artifact_key(workload, signature, device_kind,
                     lowered_sha=lowered_sha)
  config_id = config.config_id if config is not None else 'baseline'
  options = dict(config.compiler_options) if config is not None else {}

  memo_path = store.path_for(key, config_id)
  memoized = _memo_get(memo_path)
  if memoized is not None:
    # Same process, same key, unchanged file: hand back the executable
    # object already loaded — zero compiles, zero deserializations
    # (the program-keyed trace above is still paid; see the memo note).
    # ``drift`` resets: it describes the LOAD EVENT that set it (a
    # fresh compile disagreeing with a stored fingerprint), not the
    # executable — replaying it would keep a recovered workload
    # drift-flagged forever.
    artifact = dataclasses.replace(memoized, from_cache=True,
                                   outcome='hit', drift=False)
    _record_compile(telemetry, registry, workload, key, config_id,
                    'hit', 'memo', 0.0, artifact.fingerprint, False,
                    memo_path)
    return artifact

  executable, payload, reason = store.load(key, config_id)
  if executable is not None:
    artifact = CompiledArtifact(
        executable=executable, key=key, workload=workload,
        config_id=config_id, from_cache=True,
        path=store.path_for(key, config_id),
        fingerprint=payload.get('fingerprint', ''),
        hlo_text=payload.get('hlo_text'),
        compiler_options=payload.get('compiler_options'),
        outcome='hit')
    _record_compile(telemetry, registry, workload, key, config_id,
                    'hit', reason, 0.0, artifact.fingerprint, False,
                    artifact.path)
    _memo_put(artifact.path, artifact)
    return artifact

  # Miss / stale / dead executable: one AOT compile, then persist.
  if lowered is None:
    lowered = jitted.lower(*example_args)
  t0 = time.perf_counter()
  compiled = compile_lowered(lowered, options)
  compile_s = time.perf_counter() - t0
  try:
    hlo_text = compiled.as_text()
  except Exception:  # noqa: BLE001 — text is evidence, not contract
    hlo_text = None
  fingerprint = ''
  if hlo_text:
    try:
      from tensor2robot_tpu.parallel import hlo_analysis

      fingerprint = hlo_analysis.program_fingerprint(hlo_text)
    except Exception:  # noqa: BLE001
      pass

  # Fingerprint drift: the store held a READABLE payload for this exact
  # key+config (same shapes, chip, jax version) whose post-optimization
  # fingerprint differs from what the toolchain just built. The key said
  # "same program"; the compiler disagreed — first-class signal.
  drift = bool(
      payload is not None and reason == 'exec_load_failed'
      and payload.get('fingerprint') and fingerprint
      and payload['fingerprint'] != fingerprint)
  if drift:
    log_warning(
        'Fingerprint drift for workload %r (key %s): stored %s, '
        'freshly compiled %s — same key now lowers to a different '
        'program.', workload, key, payload.get('fingerprint'),
        fingerprint)

  path = ''
  if persist:
    path = store.persist(workload, key, config_id, options, compiled,
                         lowered_sha=lowered_sha, fingerprint=fingerprint,
                         hlo_text=hlo_text)
  artifact = CompiledArtifact(
      executable=compiled, key=key, workload=workload,
      config_id=config_id, from_cache=False, path=path,
      fingerprint=fingerprint, hlo_text=hlo_text,
      compiler_options=options, compile_s=compile_s,
      outcome='compiled', drift=drift)
  _record_compile(telemetry, registry, workload, key, config_id,
                  'compiled', reason, compile_s, fingerprint, drift,
                  path)
  _memo_put(path, artifact)
  return artifact
