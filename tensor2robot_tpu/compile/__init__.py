"""Unified compiled-artifact pipeline (ROADMAP item 5).

One ``CompiledArtifact`` abstraction — lowered-program hash + compiler
options + layouts + post-optimization fingerprint + serialized
executable — behind an atomic on-disk store keyed like the tuning
cache, so trainers, the autotuner sweep, serving, the RL acting step,
and forensics all cold-start from (and persist to) the same place.
Import-light: jax loads lazily inside functions, never at import.
"""

from tensor2robot_tpu.compile.artifact import (
    ARTIFACT_DIRNAME,
    ARTIFACT_HITS_COUNTER,
    ARTIFACT_MISSES_COUNTER,
    ARTIFACT_SCHEMA,
    COLDSTART_BENCH_KEYS,
    COMPILE_RECORD_KIND,
    DRIFT_COUNTER,
    FINGERPRINT_DRIFT,
    ArtifactStore,
    CompiledArtifact,
    artifact_key,
    compile_lowered,
    load_or_compile,
    program_sha,
    resolve_cache_winner,
)

__all__ = [
    'ARTIFACT_DIRNAME',
    'ARTIFACT_HITS_COUNTER',
    'ARTIFACT_MISSES_COUNTER',
    'ARTIFACT_SCHEMA',
    'COLDSTART_BENCH_KEYS',
    'COMPILE_RECORD_KIND',
    'DRIFT_COUNTER',
    'FINGERPRINT_DRIFT',
    'ArtifactStore',
    'CompiledArtifact',
    'artifact_key',
    'compile_lowered',
    'load_or_compile',
    'program_sha',
    'resolve_cache_winner',
]
