"""bfloat16 feed wrapper: present f32 specs upstream, emit bf16 downstream.

Parity target: /root/reference/preprocessors/tpu_preprocessor_wrapper.py:37-160.
In the reference this wrapper (plus models/tpu_model_wrapper.py) exists
because TF1's CPU↔TPU infeed could not carry some dtypes; in JAX, bf16 arrays
are first-class on both sides, so most models simply declare bf16 specs and
need none of this. The wrapper remains for models that keep float32 specs but
want bf16 device math: it

  * presents float32 in-specs to the (host) data pipeline, even where the
    wrapped preprocessor asks for bfloat16 (ref :78-106);
  * strips optional tensors from out-specs (TPU infeed had no optionals —
    kept because it also guarantees a static, dense feed structure, which is
    what jit wants) and re-casts float32 outputs to bfloat16 (ref :108-160).
"""

from __future__ import annotations

import numpy as np

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec, bfloat16


class Bfloat16PreprocessorWrapper(AbstractPreprocessor):
  """Decorates any preprocessor with f32-in / bf16-out spec re-typing."""

  def __init__(self, preprocessor: AbstractPreprocessor):
    super().__init__()
    self._preprocessor = preprocessor

  @property
  def preprocessor(self) -> AbstractPreprocessor:
    return self._preprocessor

  def get_in_feature_specification(self, mode):
    return specs_lib.replace_dtype(
        self._preprocessor.get_in_feature_specification(mode),
        bfloat16, np.float32)

  def get_in_label_specification(self, mode):
    return specs_lib.replace_dtype(
        self._preprocessor.get_in_label_specification(mode),
        bfloat16, np.float32)

  def _out_spec(self, spec_structure) -> SpecStruct:
    required = specs_lib.filter_required_flat_tensor_spec(spec_structure)
    return specs_lib.replace_dtype(required, np.float32, bfloat16)

  def get_out_feature_specification(self, mode):
    return self._out_spec(
        self._preprocessor.get_out_feature_specification(mode))

  def get_out_label_specification(self, mode):
    return self._out_spec(self._preprocessor.get_out_label_specification(mode))

  def _preprocess_fn(self, features, labels, mode, rng=None):
    features, labels = self._preprocessor._preprocess_fn(  # pylint: disable=protected-access
        features, labels, mode, rng)
    features = self._cast(features,
                          self.get_out_feature_specification(mode))
    if labels is not None:
      labels = self._cast(labels, self.get_out_label_specification(mode))
    return features, labels

  def _cast_in(self, tensors, in_spec) -> SpecStruct:
    """Casts to bf16 exactly where the inner's in-spec declares it,
    passing unknown keys through untouched (unlike _cast, which also
    filters to the spec's keys)."""
    flat_spec = specs_lib.flatten_spec_structure(in_spec)
    flat = specs_lib.flatten_spec_structure(tensors)
    out = SpecStruct()
    for key in flat:
      value = flat[key]
      if key in flat_spec and flat_spec[key].dtype == bfloat16:
        import jax.numpy as jnp
        value = jnp.asarray(value).astype(bfloat16)
      out[key] = value
    return out

  def _cast(self, tensors, out_spec) -> SpecStruct:
    """Keeps required tensors, casting f32->bf16 where the out-spec says so."""
    flat_spec = specs_lib.flatten_spec_structure(out_spec)
    flat = specs_lib.flatten_spec_structure(tensors)
    out = SpecStruct()
    for key in flat_spec:
      if key not in flat:
        continue
      value = flat[key]
      if flat_spec[key].dtype == bfloat16:
        import jax.numpy as jnp
        value = jnp.asarray(value).astype(bfloat16)
      out[key] = value
    return out

  def preprocess(self, features, labels, mode: str, rng=None):
    """Validate -> transform -> cast; delegates wholesale to inners that
    own their full pipeline.

    A wrapped preprocessor that OVERRIDES preprocess() (e.g.
    DeviceDecodePreprocessor, whose override accepts both sparse streams
    and dense coefficient tensors and forbids _preprocess_fn) gets called
    through its public entry point; everything else runs the inherited
    validate -> _preprocess_fn -> validate template against this
    wrapper's re-typed specs.
    """
    inner_cls = type(self._preprocessor)
    if inner_cls.preprocess is not AbstractPreprocessor.preprocess:
      # The host pipeline ships f32 where the inner asks for bf16 (this
      # wrapper's in-spec re-typing); restore the inner's declared input
      # dtypes before handing off, leaving keys the inner's in-spec does
      # not know (e.g. feed-converted dense coefficient tensors) intact.
      features = self._cast_in(
          features, self._preprocessor.get_in_feature_specification(mode))
      if labels is not None:
        labels = self._cast_in(
            labels, self._preprocessor.get_in_label_specification(mode))
      features, labels = self._preprocessor.preprocess(features, labels,
                                                       mode, rng=rng)
      features = self._cast(features,
                            self.get_out_feature_specification(mode))
      if labels is not None:
        labels = self._cast(labels, self.get_out_label_specification(mode))
      return features, labels
    return super().preprocess(features, labels, mode, rng=rng)

  def __getattr__(self, name):
    """Forwards the wrapped preprocessor's extra surface (decorator
    contract): e.g. DeviceDecodePreprocessor's
    ``raw_in_feature_specification`` / ``sparse`` / ``image_keys``, which
    the input generators introspect to plan the native coef stream. Only
    public attributes forward; missing privates raise normally."""
    if name.startswith('_'):
      raise AttributeError(name)
    return getattr(self.__dict__['_preprocessor'], name)
