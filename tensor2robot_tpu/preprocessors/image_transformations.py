"""Jittable image transformations (crops, photometric & depth distortions).

Parity target: /root/reference/preprocessors/image_transformations.py:31-332.
All functions are pure JAX on float32/bfloat16 images in [0, 1], NHWC, and
take explicit PRNG keys, so they run *on device inside the jitted train step*
(XLA fuses the elementwise chains) instead of host-side tf.data maps.

Multi-view alignment: like the reference, the Random/Center crop functions
take a *list* of image batches and apply identical offsets to every view of
the same example, keeping stereo/dual-camera inputs registered.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def _check_shapes(images: Sequence[jnp.ndarray]) -> None:
  if not images:
    raise ValueError('Need at least one image batch.')
  first = tuple(images[0].shape[:3])
  for img in images[1:]:
    if tuple(img.shape[:3]) != first:
      # Shared offsets only align views of equal spatial size; mismatched
      # views would silently crop different locations (dynamic_slice clamps).
      raise ValueError(
          'All views must share [B, H, W] for aligned crops; got {} vs {}.'
          .format(first, tuple(img.shape[:3])))


def crop_images(images: List[jnp.ndarray], offsets,
                target_shape: Tuple[int, int]) -> List[jnp.ndarray]:
  """Crops each [B,H,W,C] batch at per-example (y, x) offsets (ref :110).

  ``offsets``: int array [B, 2]. Uses per-example dynamic slices via vmap —
  static target shape keeps XLA happy.
  """
  _check_shapes(images)
  th, tw = target_shape

  def _crop_one(img, off):
    return jax.lax.dynamic_slice(
        img, (off[0], off[1], 0), (th, tw, img.shape[-1]))

  return [jax.vmap(_crop_one)(img, offsets) for img in images]


def random_crop_offsets(key: jax.Array, batch: int,
                        image_shape: Tuple[int, int],
                        target_shape: Tuple[int, int]) -> jnp.ndarray:
  """Per-example uniform (y, x) crop offsets as an int [batch, 2] array.

  Factored out of :func:`random_crop_images` so fused crop kernels
  (``preprocessors/pallas_crop.py``) sample identically to the XLA path.
  """
  height, width = image_shape
  th, tw = target_shape
  if th > height or tw > width:
    raise ValueError('Crop {} exceeds image size {}.'.format(
        target_shape, (height, width)))
  ky, kx = jax.random.split(key)
  ys = jax.random.randint(ky, (batch,), 0, height - th + 1)
  xs = jax.random.randint(kx, (batch,), 0, width - tw + 1)
  return jnp.stack([ys, xs], axis=-1)


def random_crop_images(key: jax.Array, images: List[jnp.ndarray],
                       target_shape: Tuple[int, int]) -> List[jnp.ndarray]:
  """Random crop, identical offsets across views of one example (ref :31)."""
  _check_shapes(images)
  batch, height, width = images[0].shape[0], images[0].shape[1], images[0].shape[2]
  offsets = random_crop_offsets(key, batch, (height, width), target_shape)
  return crop_images(images, offsets, target_shape)


def center_crop_images(images: List[jnp.ndarray],
                       target_shape: Tuple[int, int]) -> List[jnp.ndarray]:
  """Deterministic center crop (ref :68)."""
  _check_shapes(images)
  height, width = images[0].shape[1], images[0].shape[2]
  th, tw = target_shape
  y0, x0 = (height - th) // 2, (width - tw) // 2
  return [img[:, y0:y0 + th, x0:x0 + tw, :] for img in images]


# -- episode ([B, T, H, W, C]) crops ------------------------------------------
# One offset per EPISODE, shared across its time steps: a fixed camera does
# not jitter within an episode (vrgripper preprocessor parity, ref
# vrgripper_env_models.py:108-141).


def random_crop_episodes(key: jax.Array, episodes: jnp.ndarray,
                         target_shape: Tuple[int, int]) -> jnp.ndarray:
  """Random crop of [B, T, H, W, C] with per-episode shared offsets."""
  batch, _, height, width = episodes.shape[:4]
  th, tw = target_shape
  if th > height or tw > width:
    raise ValueError('Crop {} exceeds image size {}.'.format(
        target_shape, (height, width)))
  ky, kx = jax.random.split(key)
  ys = jax.random.randint(ky, (batch,), 0, height - th + 1)
  xs = jax.random.randint(kx, (batch,), 0, width - tw + 1)

  def _one(episode, y, x):
    return jax.lax.dynamic_slice(
        episode, (0, y, x, 0),
        (episode.shape[0], th, tw, episode.shape[3]))

  return jax.vmap(_one)(episodes, ys, xs)


def center_crop_episodes(episodes: jnp.ndarray,
                         target_shape: Tuple[int, int]) -> jnp.ndarray:
  """Deterministic center crop of [B, T, H, W, C]."""
  height, width = episodes.shape[2], episodes.shape[3]
  th, tw = target_shape
  if th > height or tw > width:
    raise ValueError('Crop {} exceeds image size {}.'.format(
        target_shape, (height, width)))
  y0, x0 = (height - th) // 2, (width - tw) // 2
  return episodes[:, :, y0:y0 + th, x0:x0 + tw, :]


# -- photometric distortions -------------------------------------------------

_RGB_TO_GRAY = jnp.asarray([0.299, 0.587, 0.114])


def rgb_to_hsv(image: jnp.ndarray) -> jnp.ndarray:
  """[..., 3] RGB in [0,1] -> HSV, matching tf.image.rgb_to_hsv semantics."""
  r, g, b = image[..., 0], image[..., 1], image[..., 2]
  maxc = jnp.maximum(jnp.maximum(r, g), b)
  minc = jnp.minimum(jnp.minimum(r, g), b)
  value = maxc
  delta = maxc - minc
  safe_delta = jnp.where(delta == 0, 1.0, delta)
  saturation = jnp.where(maxc == 0, 0.0, delta / jnp.where(maxc == 0, 1.0, maxc))
  hue_r = ((g - b) / safe_delta) % 6.0
  hue_g = (b - r) / safe_delta + 2.0
  hue_b = (r - g) / safe_delta + 4.0
  hue = jnp.where(maxc == r, hue_r, jnp.where(maxc == g, hue_g, hue_b))
  hue = jnp.where(delta == 0, 0.0, hue / 6.0)
  return jnp.stack([hue, saturation, value], axis=-1)


def hsv_to_rgb(image: jnp.ndarray) -> jnp.ndarray:
  """[..., 3] HSV -> RGB in [0,1]."""
  h, s, v = image[..., 0], image[..., 1], image[..., 2]
  h6 = h * 6.0
  c = v * s
  x = c * (1.0 - jnp.abs(h6 % 2.0 - 1.0))
  zeros = jnp.zeros_like(c)
  idx = jnp.floor(h6).astype(jnp.int32) % 6
  r = jnp.select([idx == 0, idx == 1, idx == 2, idx == 3, idx == 4, idx == 5],
                 [c, x, zeros, zeros, x, c])
  g = jnp.select([idx == 0, idx == 1, idx == 2, idx == 3, idx == 4, idx == 5],
                 [x, c, c, x, zeros, zeros])
  b = jnp.select([idx == 0, idx == 1, idx == 2, idx == 3, idx == 4, idx == 5],
                 [zeros, zeros, x, c, c, x])
  m = v - c
  return jnp.stack([r + m, g + m, b + m], axis=-1)


def adjust_brightness(image: jnp.ndarray, delta) -> jnp.ndarray:
  return image + delta


def adjust_contrast(image: jnp.ndarray, factor) -> jnp.ndarray:
  mean = jnp.mean(image, axis=(-3, -2), keepdims=True)
  return (image - mean) * factor + mean


def adjust_saturation(image: jnp.ndarray, factor) -> jnp.ndarray:
  gray = jnp.tensordot(image, _RGB_TO_GRAY, axes=[[-1], [0]],
                       precision=jax.lax.Precision.HIGHEST)[..., None]
  return gray + (image - gray) * factor


def adjust_hue(image: jnp.ndarray, delta) -> jnp.ndarray:
  """Circular hue shift by ``delta`` turns (tf.image.adjust_hue semantics).

  Pure elementwise HSV round trip — XLA fuses the whole chain, so on TPU this
  costs one pass over the image, no matmul.
  """
  hsv = rgb_to_hsv(image)
  hue = (hsv[..., 0] + delta) % 1.0
  return hsv_to_rgb(jnp.stack([hue, hsv[..., 1], hsv[..., 2]], axis=-1))


def apply_photometric_image_distortions(
    key: jax.Array,
    images: List[jnp.ndarray],
    random_brightness: bool = False,
    max_delta_brightness: float = 0.125,
    random_saturation: bool = False,
    lower_saturation: float = 0.5,
    upper_saturation: float = 1.5,
    random_hue: bool = False,
    max_delta_hue: float = 0.2,
    random_contrast: bool = False,
    lower_contrast: float = 0.5,
    upper_contrast: float = 1.5,
    random_noise_level: float = 0.0,
    random_noise_apply_probability: float = 0.5,
    random_channel_swap: bool = False,
) -> List[jnp.ndarray]:
  """Per-example random photometric jitter on [0,1] images (ref :182-273).

  Each image batch in ``images`` is distorted independently (unlike crops,
  photometric jitter need not be aligned across views — reference parity).
  """
  out = []
  for img in images:
    batch = img.shape[0]
    if random_brightness:
      key, sub = jax.random.split(key)
      delta = jax.random.uniform(sub, (batch, 1, 1, 1),
                                 minval=-max_delta_brightness,
                                 maxval=max_delta_brightness)
      img = adjust_brightness(img, delta)
    if random_saturation:
      key, sub = jax.random.split(key)
      factor = jax.random.uniform(sub, (batch, 1, 1, 1),
                                  minval=lower_saturation,
                                  maxval=upper_saturation)
      img = adjust_saturation(img, factor)
    if random_hue:
      key, sub = jax.random.split(key)
      delta = jax.random.uniform(sub, (batch,), minval=-max_delta_hue,
                                 maxval=max_delta_hue)
      img = jax.vmap(adjust_hue)(img, delta)
    if random_contrast:
      key, sub = jax.random.split(key)
      factor = jax.random.uniform(sub, (batch, 1, 1, 1),
                                  minval=lower_contrast, maxval=upper_contrast)
      img = adjust_contrast(img, factor)
    if random_noise_level:
      key, knoise, kapply = jax.random.split(key, 3)
      noise = jax.random.normal(knoise, img.shape, img.dtype) * random_noise_level
      apply = (jax.random.uniform(kapply, (batch, 1, 1, 1))
               < random_noise_apply_probability)
      img = jnp.where(apply, img + noise, img)
    if random_channel_swap:
      key, sub = jax.random.split(key)
      # All 6 permutations of RGB; pick one per example.
      perms = jnp.asarray([[0, 1, 2], [0, 2, 1], [1, 0, 2],
                           [1, 2, 0], [2, 0, 1], [2, 1, 0]])
      choice = jax.random.randint(sub, (batch,), 0, perms.shape[0])
      img = jax.vmap(lambda im, p: im[..., p])(img, perms[choice])
    img = jnp.clip(img, 0.0, 1.0)
    out.append(img)
  return out


def apply_depth_image_distortions(
    key: jax.Array,
    depth_images: List[jnp.ndarray],
    random_noise_level: float = 0.05,
    random_noise_apply_probability: float = 0.5,
    scale_noise: bool = False,
    lower_scale: float = 0.8,
    upper_scale: float = 1.2,
) -> List[jnp.ndarray]:
  """Gaussian / scale noise on [B,H,W,1] depth maps (ref :276-332)."""
  out = []
  for img in depth_images:
    batch = img.shape[0]
    if random_noise_level:
      key, knoise, kapply = jax.random.split(key, 3)
      noise = jax.random.normal(knoise, img.shape, img.dtype) * random_noise_level
      apply = (jax.random.uniform(kapply, (batch, 1, 1, 1))
               < random_noise_apply_probability)
      img = jnp.where(apply, img + noise, img)
    if scale_noise:
      key, sub = jax.random.split(key)
      scale = jax.random.uniform(sub, (batch, 1, 1, 1), minval=lower_scale,
                                 maxval=upper_scale)
      img = img * scale
    out.append(img)
  return out
