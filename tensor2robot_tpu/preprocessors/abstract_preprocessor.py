"""Preprocessor protocol: declarative in/out specs around a pure transform.

Parity target: /root/reference/preprocessors/abstract_preprocessor.py:34-223.
A preprocessor declares four spec structures — in/out × features/labels, per
mode — and ``preprocess`` runs validate_and_pack → ``_preprocess_fn`` →
validate_and_flatten on both sides of the transform.

TPU-first redesign: ``_preprocess_fn`` is a *pure jittable function* taking an
explicit ``rng`` key, so the trainer composes it INSIDE the jitted train step:
random crops/distortions execute on device, fused by XLA, instead of on host
CPU as in the reference's tf.data map (utils/tfdata.py:572-574). Validation
under jit happens at trace time and costs nothing at runtime.
"""

from __future__ import annotations

import abc
from typing import Optional, Tuple

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.modes import assert_valid_mode
from tensor2robot_tpu.specs.struct import SpecStruct


class AbstractPreprocessor(abc.ABC):
  """Base class; subclasses define specs + _preprocess_fn."""

  def __init__(self,
               model_feature_specification_fn=None,
               model_label_specification_fn=None):
    """Optionally binds the model's spec fns (mode -> spec structure).

    ref: abstract_preprocessor.py:42-58 — preprocessors are constructed with
    the model's spec getters so out-specs can default to the model's needs.
    """
    self._model_feature_specification_fn = model_feature_specification_fn
    self._model_label_specification_fn = model_label_specification_fn

  # -- the four spec declarations -------------------------------------------

  @abc.abstractmethod
  def get_in_feature_specification(self, mode: str) -> SpecStruct:
    """What the raw data pipeline must produce (ref :93)."""

  @abc.abstractmethod
  def get_in_label_specification(self, mode: str) -> SpecStruct:
    """ref :105."""

  @abc.abstractmethod
  def get_out_feature_specification(self, mode: str) -> SpecStruct:
    """What the model consumes (ref :117)."""

  @abc.abstractmethod
  def get_out_label_specification(self, mode: str) -> SpecStruct:
    """ref :129."""

  def _model_feature_specification(self, mode: str):
    if self._model_feature_specification_fn is None:
      raise ValueError(
          '{} was not constructed with model spec fns.'.format(type(self)))
    return self._model_feature_specification_fn(mode)

  def _model_label_specification(self, mode: str):
    if self._model_label_specification_fn is None:
      raise ValueError(
          '{} was not constructed with model spec fns.'.format(type(self)))
    return self._model_label_specification_fn(mode)

  # -- the transform ---------------------------------------------------------

  @abc.abstractmethod
  def _preprocess_fn(self, features: SpecStruct,
                     labels: Optional[SpecStruct],
                     mode: str,
                     rng=None) -> Tuple[SpecStruct, Optional[SpecStruct]]:
    """Pure transform; must be jittable (no data-dependent python control flow)."""

  def preprocess(self, features, labels, mode: str,
                 rng=None) -> Tuple[SpecStruct, Optional[SpecStruct]]:
    """Validated transform (ref :177-223)."""
    assert_valid_mode(mode)
    features = specs_lib.validate_and_pack(
        self.get_in_feature_specification(mode), features, ignore_batch=True)
    if labels is not None and len(specs_lib.flatten_spec_structure(
        self.get_in_label_specification(mode))):
      labels = specs_lib.validate_and_pack(
          self.get_in_label_specification(mode), labels, ignore_batch=True)
    else:
      labels = None
    features_out, labels_out = self._preprocess_fn(features, labels, mode, rng)
    features_out = specs_lib.validate_and_pack(
        self.get_out_feature_specification(mode), features_out,
        ignore_batch=True)
    if labels_out is not None:
      labels_out = specs_lib.validate_and_pack(
          self.get_out_label_specification(mode), labels_out,
          ignore_batch=True)
    return features_out, labels_out
