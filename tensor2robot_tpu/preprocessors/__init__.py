"""Preprocessors: validated, jittable transforms between data and model specs."""

from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.preprocessors.noop_preprocessor import NoOpPreprocessor
from tensor2robot_tpu.preprocessors.spec_transformation_preprocessor import (
    SpecTransformationPreprocessor,
)
from tensor2robot_tpu.preprocessors.bfloat16_wrapper import (
    Bfloat16PreprocessorWrapper,
)
from tensor2robot_tpu.preprocessors import image_transformations
from tensor2robot_tpu.preprocessors.device_decode import (
    DeviceDecodePreprocessor,
)
