"""Preprocessor whose in-specs are derived by transforming the model's specs.

Parity: /root/reference/preprocessors/spec_transformation_preprocessor.py:30.
Subclasses override ``update_spec_transform`` to declare how each model
(out) spec looks on disk — e.g. the model wants a float32 (H, W, 3) image but
the dataset stores jpeg bytes at a different resolution.
"""

from __future__ import annotations

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec


class SpecTransformationPreprocessor(AbstractPreprocessor):

  def update_spec_transform(self, key: str, spec: TensorSpec,
                            mode: str) -> TensorSpec:
    """Maps one model spec to its on-disk (in) spec. Default: identity."""
    del key, mode
    return spec

  def _transform(self, spec_structure, mode: str) -> SpecStruct:
    flat = specs_lib.flatten_spec_structure(spec_structure)
    out = SpecStruct()
    for key in flat:
      out[key] = self.update_spec_transform(key, flat[key], mode)
    return specs_lib.add_sequence_length_specs(out)

  def get_in_feature_specification(self, mode):
    return self._transform(self._model_feature_specification(mode), mode)

  def get_in_label_specification(self, mode):
    return self._transform(self._model_label_specification(mode), mode)

  def get_out_feature_specification(self, mode):
    return specs_lib.add_sequence_length_specs(
        self._model_feature_specification(mode))

  def get_out_label_specification(self, mode):
    return specs_lib.add_sequence_length_specs(
        self._model_label_specification(mode))
