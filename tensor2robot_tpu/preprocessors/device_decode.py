"""DeviceDecodePreprocessor: train straight from DCT coefficients.

The trainable half of the split-decode input path (SURVEY hard-part #3).
Wrapping a model's preprocessor::

    model.set_preprocessor(DeviceDecodePreprocessor(model.preprocessor))

changes its IN-specs so the input pipeline ships quantized JPEG
coefficient blocks instead of decoded pixels — the native loader's
``image_mode='coef'`` output (data/native/record_loader.cc stops after
the entropy stage, ~1.5x host throughput) — and finishes the decode
(dequant + 8x8 IDCT on the MXU + chroma upsample + YCbCr->RGB,
data/jpeg_device.py) INSIDE the jitted train step before the wrapped
preprocessor runs. DefaultRecordInputGenerator detects the wrapper and
plans the native loader in coef mode automatically.

Eligible image specs: rank-3 uint8 JPEG with H and W divisible by 16
(baseline 4:2:0). Other specs pass through untouched.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from tensor2robot_tpu.data import jpeg_device
from tensor2robot_tpu.data.native_loader import coef_eligible
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.specs import algebra
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec


def coef_specs(key: str, spec: TensorSpec) -> SpecStruct:
  """The four coefficient tensors replacing one image spec."""
  h, w, _ = spec.shape
  out = SpecStruct()
  out[key + '/y'] = TensorSpec((h // 8, w // 8, 64), np.int16,
                               name=(spec.name or key) + '/y')
  out[key + '/cb'] = TensorSpec((h // 16, w // 16, 64), np.int16,
                                name=(spec.name or key) + '/cb')
  out[key + '/cr'] = TensorSpec((h // 16, w // 16, 64), np.int16,
                                name=(spec.name or key) + '/cr')
  out[key + '/qt'] = TensorSpec((3, 64), np.uint16,
                                name=(spec.name or key) + '/qt')
  return out


def sparse_coef_specs(key: str, spec: TensorSpec) -> SpecStruct:
  """The four sparse-stream tensors replacing one image spec.

  The entry dim is dynamic (bucketed per batch by the native loader) and
  declared None; the fixed-shape dense tensors the train step consumes are
  produced by data/device_feed.py between transfer and step.
  """
  out = SpecStruct()
  name = spec.name or key
  out[key + '/sd'] = TensorSpec((None,), np.uint8, name=name + '/sd')
  out[key + '/sv'] = TensorSpec((None,), np.int8, name=name + '/sv')
  out[key + '/qt'] = TensorSpec((3, 64), np.uint16, name=name + '/qt')
  out[key + '/n'] = TensorSpec((), np.int32, name=name + '/n')
  return out


def packed_coef_specs(key: str, spec: TensorSpec) -> SpecStruct:
  """The four packed-wire tensors replacing one image spec.

  The nibble/escape stream dims are dynamic (bucketed per batch by the
  native loader) and declared None; the DC-delta plane is fixed (one
  nibble per block, two per byte); the quant table is batch-HOISTED —
  it ships as a single [1, 3, 64] array per batch, not per example
  (data/native_loader.py _hoisted_quant_table), and the device-side
  unpack broadcasts it back before the jitted step.
  """
  from tensor2robot_tpu.data.native_loader import packed_dc_count

  out = SpecStruct()
  name = spec.name or key
  out[key + '/pw'] = TensorSpec((None,), np.uint8, name=name + '/pw')
  out[key + '/se'] = TensorSpec((None,), np.int16, name=name + '/se')
  out[key + '/dcn'] = TensorSpec((packed_dc_count(spec) // 2,), np.uint8,
                                 name=name + '/dcn')
  out[key + '/qt'] = TensorSpec((3, 64), np.uint16, name=name + '/qt')
  return out


def wrap_model_with_device_decode(model=None, sparse: bool = True,
                                  sparse_density: float = 0.5,
                                  wire_format: str = None):
  """Config-surface helper: switch a model to the split-decode input path.

  Gin usage (the one-line production wiring)::

      train_eval_model.t2r_model = @wrap_model_with_device_decode()
      wrap_model_with_device_decode.model = @Grasping44...()

  With ``sparse=True`` (default) the input pipeline ships bucketed sparse
  DCT entry streams — ~8x fewer host->device bytes on camera frames; the
  Trainer unpacks them between transfer and the jitted step.
  ``wire_format='packed'`` selects the bit-packed wire instead (~1.8x
  fewer bytes again; requires batch-uniform JPEG quant tables — see
  docs/performance.md "Transfer path").
  """
  if model is None:
    raise ValueError('wrap_model_with_device_decode requires a model.')
  model.set_preprocessor(
      DeviceDecodePreprocessor(model.preprocessor, sparse=sparse,
                               sparse_density=sparse_density,
                               wire_format=wire_format))
  return model


class DeviceDecodePreprocessor(AbstractPreprocessor):
  """Wraps a preprocessor to accept coefficient inputs (module docstring).

  ``sparse=True`` additionally ships the coefficients as sparse
  delta/value entry streams (~8x fewer host->device bytes on realistic
  camera frames; data/native/record_loader.cc decode_jpeg_coef_sparse);
  ``wire_format='packed'`` tightens that to the bit-packed wire
  (nibble-coded entries, DC-delta plane, batch-hoisted quant tables —
  ~1.8x fewer bytes again; decode_jpeg_coef_packed). Either way the
  Trainer unpacks to dense coefficient tensors right after transfer
  (data/device_feed.py) so the train step never sees the dynamic
  bucketed shapes; host-side ``preprocess`` calls also accept sparse or
  packed features directly for tests and numpy pipelines.
  """

  def __init__(self, inner: AbstractPreprocessor, sparse: bool = False,
               sparse_density: float = 0.5, wire_format: str = None):
    super().__init__(inner._model_feature_specification_fn,
                     inner._model_label_specification_fn)
    self._inner = inner
    # ``wire_format`` is the one authority ('dense' | 'sparse' |
    # 'packed'); the ``sparse`` bool remains as the original config
    # surface and maps onto it when wire_format is not given.
    if wire_format is None:
      wire_format = 'sparse' if sparse else 'dense'
    if wire_format not in ('dense', 'sparse', 'packed'):
      raise ValueError(
          "wire_format must be 'dense', 'sparse' or 'packed'; got {!r}."
          .format(wire_format))
    self.wire_format = wire_format
    self.sparse = wire_format == 'sparse'
    # Entry capacity as a fraction of the total coefficient count; the
    # input generator passes it to the native loader plan. Camera frames
    # run ~12-14% nonzero; raise toward 1.0 for unusually dense imagery
    # (the loader errors with a clear message on overflow).
    self.sparse_density = float(sparse_density)
    keys = self.image_keys('train')
    if not keys:
      raise ValueError(
          'DeviceDecodePreprocessor: the wrapped preprocessor declares no '
          'coef-eligible image specs (rank-3 uint8 JPEG, dims % 16 == 0).')
    # Fail at wrap time, naming the offenders: the coef record loader
    # rejects a plan containing ANY non-eligible encoded image, so a
    # mixed spec set would otherwise surface as a late, generic error at
    # iterator creation.
    spec = algebra.flatten_spec_structure(
        self._inner.get_in_feature_specification('train'))
    ineligible = [key for key in spec
                  if spec[key].is_encoded_image
                  and not coef_eligible(spec[key])]
    if ineligible:
      raise ValueError(
          'DeviceDecodePreprocessor: encoded-image specs {} are not '
          'coef-eligible (need rank-3 uint8 3-channel JPEG with dims '
          'divisible by 16); split decode requires ALL images eligible.'
          .format(ineligible))

  @property
  def inner(self) -> AbstractPreprocessor:
    return self._inner

  def image_keys(self, mode: str) -> List[str]:
    spec = algebra.flatten_spec_structure(
        self._inner.get_in_feature_specification(mode))
    return [key for key in spec if coef_eligible(spec[key])]

  def raw_in_feature_specification(self, mode: str) -> SpecStruct:
    """The inner (on-disk JPEG) in-specs — what the record loader plans."""
    return self._inner.get_in_feature_specification(mode)

  def get_in_feature_specification(self, mode: str) -> SpecStruct:
    spec = algebra.flatten_spec_structure(
        self._inner.get_in_feature_specification(mode))
    make_specs = {'sparse': sparse_coef_specs,
                  'packed': packed_coef_specs,
                  'dense': coef_specs}[self.wire_format]
    out = SpecStruct()
    for key in spec:
      if coef_eligible(spec[key]):
        for ckey, cspec in make_specs(key, spec[key]).items():
          out[ckey] = cspec
      else:
        out[key] = spec[key]
    return out

  def get_in_label_specification(self, mode: str) -> SpecStruct:
    return self._inner.get_in_label_specification(mode)

  def get_out_feature_specification(self, mode: str) -> SpecStruct:
    return self._inner.get_out_feature_specification(mode)

  def get_out_label_specification(self, mode: str) -> SpecStruct:
    return self._inner.get_out_label_specification(mode)

  def preprocess(self, features, labels, mode: str, rng=None
                 ) -> Tuple[SpecStruct, SpecStruct]:
    """Finish the JPEG decode on device, then run the wrapped preprocessor
    (which validates against its own in-specs)."""
    features = SpecStruct(**{k: features[k] for k in features})
    keys = self.image_keys(mode)
    if any(key + '/sd' in features or key + '/pw' in features
           for key in keys):
      # Sparse/packed streams straight from the loader (host/test
      # convenience; the Trainer path unpacks BEFORE the jitted step via
      # data/device_feed.py to keep the step shape-stable).
      spec = algebra.flatten_spec_structure(
          self._inner.get_in_feature_specification(mode))
      shapes = {key: (spec[key].shape[0], spec[key].shape[1])
                for key in keys}
      if any(key + '/pw' in features for key in keys):
        features = jpeg_device.unpack_packed_features(features, shapes)
      else:
        features = jpeg_device.unpack_sparse_features(features, shapes)
    features = jpeg_device.decode_coef_features(features, keys)
    return self._inner.preprocess(features, labels, mode, rng=rng)

  def _preprocess_fn(self, features, labels, mode: str, rng=None):
    raise AssertionError(
        'DeviceDecodePreprocessor overrides preprocess() directly.')
