"""DeviceDecodePreprocessor: train straight from DCT coefficients.

The trainable half of the split-decode input path (SURVEY hard-part #3).
Wrapping a model's preprocessor::

    model.set_preprocessor(DeviceDecodePreprocessor(model.preprocessor))

changes its IN-specs so the input pipeline ships quantized JPEG
coefficient blocks instead of decoded pixels — the native loader's
``image_mode='coef'`` output (data/native/record_loader.cc stops after
the entropy stage, ~1.5x host throughput) — and finishes the decode
(dequant + 8x8 IDCT on the MXU + chroma upsample + YCbCr->RGB,
data/jpeg_device.py) INSIDE the jitted train step before the wrapped
preprocessor runs. DefaultRecordInputGenerator detects the wrapper and
plans the native loader in coef mode automatically.

Eligible image specs: rank-3 uint8 JPEG with H and W divisible by 16
(baseline 4:2:0). Other specs pass through untouched.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from tensor2robot_tpu.data import jpeg_device
from tensor2robot_tpu.data.native_loader import coef_eligible
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.specs import algebra
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec


def coef_specs(key: str, spec: TensorSpec) -> SpecStruct:
  """The four coefficient tensors replacing one image spec."""
  h, w, _ = spec.shape
  out = SpecStruct()
  out[key + '/y'] = TensorSpec((h // 8, w // 8, 64), np.int16,
                               name=(spec.name or key) + '/y')
  out[key + '/cb'] = TensorSpec((h // 16, w // 16, 64), np.int16,
                                name=(spec.name or key) + '/cb')
  out[key + '/cr'] = TensorSpec((h // 16, w // 16, 64), np.int16,
                                name=(spec.name or key) + '/cr')
  out[key + '/qt'] = TensorSpec((3, 64), np.uint16,
                                name=(spec.name or key) + '/qt')
  return out


def sparse_coef_specs(key: str, spec: TensorSpec) -> SpecStruct:
  """The four sparse-stream tensors replacing one image spec.

  The entry dim is dynamic (bucketed per batch by the native loader) and
  declared None; the fixed-shape dense tensors the train step consumes are
  produced by data/device_feed.py between transfer and step.
  """
  out = SpecStruct()
  name = spec.name or key
  out[key + '/sd'] = TensorSpec((None,), np.uint8, name=name + '/sd')
  out[key + '/sv'] = TensorSpec((None,), np.int8, name=name + '/sv')
  out[key + '/qt'] = TensorSpec((3, 64), np.uint16, name=name + '/qt')
  out[key + '/n'] = TensorSpec((), np.int32, name=name + '/n')
  return out


def wrap_model_with_device_decode(model=None, sparse: bool = True,
                                  sparse_density: float = 0.5):
  """Config-surface helper: switch a model to the split-decode input path.

  Gin usage (the one-line production wiring)::

      train_eval_model.t2r_model = @wrap_model_with_device_decode()
      wrap_model_with_device_decode.model = @Grasping44...()

  With ``sparse=True`` (default) the input pipeline ships bucketed sparse
  DCT entry streams — ~8x fewer host->device bytes on camera frames; the
  Trainer unpacks them between transfer and the jitted step.
  """
  if model is None:
    raise ValueError('wrap_model_with_device_decode requires a model.')
  model.set_preprocessor(
      DeviceDecodePreprocessor(model.preprocessor, sparse=sparse,
                               sparse_density=sparse_density))
  return model


class DeviceDecodePreprocessor(AbstractPreprocessor):
  """Wraps a preprocessor to accept coefficient inputs (module docstring).

  ``sparse=True`` additionally ships the coefficients as sparse
  delta/value entry streams (~8x fewer host->device bytes on realistic
  camera frames; data/native/record_loader.cc decode_jpeg_coef_sparse).
  The Trainer unpacks them to dense coefficient tensors right after
  transfer (data/device_feed.py) so the train step never sees the
  dynamic bucketed shapes; host-side ``preprocess`` calls also accept
  sparse features directly for tests and numpy pipelines.
  """

  def __init__(self, inner: AbstractPreprocessor, sparse: bool = False,
               sparse_density: float = 0.5):
    super().__init__(inner._model_feature_specification_fn,
                     inner._model_label_specification_fn)
    self._inner = inner
    self.sparse = bool(sparse)
    # Entry capacity as a fraction of the total coefficient count; the
    # input generator passes it to the native loader plan. Camera frames
    # run ~12-14% nonzero; raise toward 1.0 for unusually dense imagery
    # (the loader errors with a clear message on overflow).
    self.sparse_density = float(sparse_density)
    keys = self.image_keys('train')
    if not keys:
      raise ValueError(
          'DeviceDecodePreprocessor: the wrapped preprocessor declares no '
          'coef-eligible image specs (rank-3 uint8 JPEG, dims % 16 == 0).')
    # Fail at wrap time, naming the offenders: the coef record loader
    # rejects a plan containing ANY non-eligible encoded image, so a
    # mixed spec set would otherwise surface as a late, generic error at
    # iterator creation.
    spec = algebra.flatten_spec_structure(
        self._inner.get_in_feature_specification('train'))
    ineligible = [key for key in spec
                  if spec[key].is_encoded_image
                  and not coef_eligible(spec[key])]
    if ineligible:
      raise ValueError(
          'DeviceDecodePreprocessor: encoded-image specs {} are not '
          'coef-eligible (need rank-3 uint8 3-channel JPEG with dims '
          'divisible by 16); split decode requires ALL images eligible.'
          .format(ineligible))

  @property
  def inner(self) -> AbstractPreprocessor:
    return self._inner

  def image_keys(self, mode: str) -> List[str]:
    spec = algebra.flatten_spec_structure(
        self._inner.get_in_feature_specification(mode))
    return [key for key in spec if coef_eligible(spec[key])]

  def raw_in_feature_specification(self, mode: str) -> SpecStruct:
    """The inner (on-disk JPEG) in-specs — what the record loader plans."""
    return self._inner.get_in_feature_specification(mode)

  def get_in_feature_specification(self, mode: str) -> SpecStruct:
    spec = algebra.flatten_spec_structure(
        self._inner.get_in_feature_specification(mode))
    make_specs = sparse_coef_specs if self.sparse else coef_specs
    out = SpecStruct()
    for key in spec:
      if coef_eligible(spec[key]):
        for ckey, cspec in make_specs(key, spec[key]).items():
          out[ckey] = cspec
      else:
        out[key] = spec[key]
    return out

  def get_in_label_specification(self, mode: str) -> SpecStruct:
    return self._inner.get_in_label_specification(mode)

  def get_out_feature_specification(self, mode: str) -> SpecStruct:
    return self._inner.get_out_feature_specification(mode)

  def get_out_label_specification(self, mode: str) -> SpecStruct:
    return self._inner.get_out_label_specification(mode)

  def preprocess(self, features, labels, mode: str, rng=None
                 ) -> Tuple[SpecStruct, SpecStruct]:
    """Finish the JPEG decode on device, then run the wrapped preprocessor
    (which validates against its own in-specs)."""
    features = SpecStruct(**{k: features[k] for k in features})
    keys = self.image_keys(mode)
    if any(key + '/sd' in features for key in keys):
      # Sparse streams straight from the loader (host/test convenience;
      # the Trainer path unpacks BEFORE the jitted step via
      # data/device_feed.py to keep the step shape-stable).
      spec = algebra.flatten_spec_structure(
          self._inner.get_in_feature_specification(mode))
      features = jpeg_device.unpack_sparse_features(
          features,
          {key: (spec[key].shape[0], spec[key].shape[1]) for key in keys})
    features = jpeg_device.decode_coef_features(features, keys)
    return self._inner.preprocess(features, labels, mode, rng=rng)

  def _preprocess_fn(self, features, labels, mode: str, rng=None):
    raise AssertionError(
        'DeviceDecodePreprocessor overrides preprocess() directly.')
