"""Identity preprocessor — the default for every model.

Parity: /root/reference/preprocessors/noop_preprocessor.py:32 — in-specs equal
the model's specs (with sequence-length companions added), and the transform
is the identity.
"""

from __future__ import annotations

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)


class NoOpPreprocessor(AbstractPreprocessor):

  def get_in_feature_specification(self, mode):
    return specs_lib.add_sequence_length_specs(
        self._model_feature_specification(mode))

  def get_in_label_specification(self, mode):
    return specs_lib.add_sequence_length_specs(
        self._model_label_specification(mode))

  def get_out_feature_specification(self, mode):
    return self.get_in_feature_specification(mode)

  def get_out_label_specification(self, mode):
    return self.get_in_label_specification(mode)

  def _preprocess_fn(self, features, labels, mode, rng=None):
    return features, labels
