"""Fused per-example crop + dtype convert as one Pallas HBM pass.

The reference crops with ``tf.image.crop_to_bounding_box`` on host CPU
(ref preprocessors/image_transformations.py:110 ``crop_image``); our
device-side equivalent (`preprocessors/image_transformations.py`
``crop_images``) vmaps ``lax.dynamic_slice`` over the batch, which XLA
lowers to a sequential while-loop over examples, followed by a separate
uint8->float convert + conv-input relayout — together ~10 ms of the
batch-512 QT-Opt train step (docs/performance.md per-op table).

This kernel does the whole thing in one pipelined pass: each grid step
pulls one uint8 frame into VMEM, rotates rows/lanes by the example's
(y, x) crop offset (``pltpu.roll`` — the only Mosaic-expressible dynamic
shift on the lane axis), keeps the leading [th, tw*C] window, converts to
float and scales. HBM traffic is the uint8 read + float write of the crop
window, with no sequential batch loop and no post-hoc convert pass.

Measured (chained on-device timing, [64, 512, 640, 3] u8 -> [64, 472,
472, 3] f32, v5e): 3.3 ms vs 24.5 ms for the XLA dynamic-slice path in
isolation — but ~3% SLOWER inside the full batch-512 QT-Opt train step
(183.6 ms f32-out / 180.3 ms bf16-out vs 178.4 ms), where XLA fuses the
convert into neighboring ops and the opaque pallas_call re-introduces a
fusion barrier + conv1-input relayout. The QT-Opt preprocessor therefore
defaults this OFF (docs/performance.md "Measured dead ends"); the kernel
stays as the measured record and for pipelines whose crop is not
adjacent to a large fusible program.

Mosaic constraints that shaped the kernel (jax 0.9):

* dynamic ``pltpu.roll`` shifts must be NON-NEGATIVE — negative dynamic
  shifts are not rejected but silently wrap at 256, so left-rolls are
  expressed as right-rolls by ``size - shift``;
* there is no direct uint8->float cast; the convert routes through int32;
* the (W, C) minor dims are viewed as one W*C lane axis so C=3 frames use
  full vector lanes instead of 3/128 of them.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def supported(image_shape: Tuple[int, ...]) -> bool:
  """True if the fused kernel handles [B, H, W, C] efficiently.

  Conservative: full-lane rows (W*C % 128 == 0) and sublane-aligned
  heights (H % 8 == 0). Anything else falls back to the XLA path.
  """
  if len(image_shape) != 4:
    return False
  _, h, w, c = image_shape
  return (w * c) % 128 == 0 and h % 8 == 0


def _crop_kernel(offs_ref, img_ref, out_ref, *, h: int, wc: int, th: int,
                 twc: int, denom: float, out_dtype):
  b = pl.program_id(0)
  oy = offs_ref[b, 0]
  x = img_ref[0]  # [H, W*C] uint8
  x = x.astype(jnp.int32)
  # Row crop first (cheaper: rotates u32 sublanes before the lane rotate).
  x = pltpu.roll(x, shift=(h - oy) % h, axis=0)
  x = x[:th, :]
  # Column crop: left-roll by ox*C lanes, expressed non-negatively.
  x = pltpu.roll(x, shift=(wc - offs_ref[b, 1]) % wc, axis=1)
  x = x[:, :twc]
  # Divide (not multiply-by-reciprocal) for bit-parity with the XLA
  # path's ``image / 255.0``.
  out_ref[0] = (x.astype(jnp.float32) / np.float32(denom)).astype(out_dtype)


def fused_crop_convert(images: jax.Array, offsets: jax.Array,
                       target_shape: Tuple[int, int],
                       out_dtype=jnp.float32,
                       denom: float = 255.0,
                       interpret: Optional[bool] = None) -> jax.Array:
  """Crops [B, H, W, C] uint8 at per-example (y, x) and converts in one pass.

  Returns ``images[b, y:y+th, x:x+tw].astype(out_dtype) / denom`` with
  static output shape [B, th, tw, C]. Offsets are clamped to the valid
  range like ``lax.dynamic_slice`` so the contract matches the XLA path.
  """
  b, h, w, c = images.shape
  th, tw = target_shape
  if images.dtype != jnp.uint8:
    raise ValueError('fused_crop_convert expects uint8 images, got {}.'
                     .format(images.dtype))
  if not supported(images.shape):
    raise ValueError('Unsupported image shape {} (need W*C % 128 == 0 and '
                     'H % 8 == 0); use crop_images instead.'
                     .format(images.shape))
  if interpret is None:
    interpret = jax.default_backend() == 'cpu'

  offsets = jnp.asarray(offsets, jnp.int32)
  offsets = jnp.clip(offsets, 0,
                     jnp.asarray([h - th, w - tw], jnp.int32))
  # Pre-scale the x offset to lanes; the kernel sees (row, lane) offsets.
  offsets = offsets * jnp.asarray([1, c], jnp.int32)

  wc, twc = w * c, tw * c
  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=1,
      grid=(b,),
      in_specs=[pl.BlockSpec((1, h, wc), lambda i, offs: (i, 0, 0))],
      out_specs=pl.BlockSpec((1, th, twc), lambda i, offs: (i, 0, 0)),
  )
  kernel = functools.partial(_crop_kernel, h=h, wc=wc, th=th, twc=twc,
                             denom=denom, out_dtype=out_dtype)
  out = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=jax.ShapeDtypeStruct((b, th, twc), out_dtype),
      interpret=interpret,
  )(offsets, images.reshape(b, h, wc))
  return out.reshape(b, th, tw, c)
