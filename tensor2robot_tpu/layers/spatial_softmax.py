"""Spatial-softmax keypoint extraction.

Parity target: /root/reference/layers/spatial_softmax.py:34
(BuildSpatialSoftmax + gumbel variant). The computation is one fused
softmax + two weighted reductions — XLA fuses the position-grid multiplies
into the softmax's normalization pass, so activations stream through VMEM
once; no Pallas needed at these map sizes.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _position_grids(num_rows: int, num_cols: int, dtype):
  """x/y coordinate grids in [-1, 1], matching the reference layout."""
  cols = jnp.linspace(-1.0, 1.0, num_cols, dtype=dtype)
  rows = jnp.linspace(-1.0, 1.0, num_rows, dtype=dtype)
  x_pos = jnp.tile(cols[None, :], (num_rows, 1)).reshape(-1)
  y_pos = jnp.tile(rows[:, None], (1, num_cols)).reshape(-1)
  return x_pos, y_pos


def spatial_softmax(features: jnp.ndarray,
                    temperature: float = 1.0,
                    gumbel_rng: Optional[jax.Array] = None
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
  """Expected 2D feature locations via softmax attention over the map.

  Args:
    features: [batch, num_rows, num_cols, channels].
    temperature: softmax temperature.
    gumbel_rng: if set, samples locations stochastically by perturbing the
      logits with Gumbel noise (the RelaxedOneHotCategorical sample of the
      reference, temperature fixed at 1.0 there).

  Returns:
    (expected_points [batch, 2*channels] laid out [x1..xC, y1..yC],
     softmax maps [batch, num_rows, num_cols, channels]).
  """
  batch, num_rows, num_cols, channels = features.shape
  dtype = features.dtype
  x_pos, y_pos = _position_grids(num_rows, num_cols, dtype)
  # [B, H, W, C] -> [B, C, H*W]: one batched softmax over locations.
  logits = jnp.transpose(features, (0, 3, 1, 2)).reshape(
      batch, channels, num_rows * num_cols)
  logits = logits / jnp.asarray(temperature, dtype)
  if gumbel_rng is not None:
    gumbel = jax.random.gumbel(gumbel_rng, logits.shape, dtype)
    logits = logits + gumbel
  attention = jax.nn.softmax(logits, axis=-1)
  expected_x = jnp.sum(attention * x_pos, axis=-1)   # [B, C]
  expected_y = jnp.sum(attention * y_pos, axis=-1)   # [B, C]
  expected_points = jnp.concatenate([expected_x, expected_y], axis=-1)
  softmax_maps = jnp.transpose(
      attention.reshape(batch, channels, num_rows, num_cols), (0, 2, 3, 1))
  return expected_points, softmax_maps
