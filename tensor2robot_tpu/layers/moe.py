"""Mixture-of-Experts MLP with expert parallelism over the mesh.

The reference has no MoE; this is part of the build-side mandate that
distributed training be first-class (SURVEY.md §5 build goals), filling
the 'ep' slot next to dp/fsdp/tp/sp. The design is the GShard/Switch
dispatch in its TPU-native form:

* **Static shapes everywhere.** Routing uses one-hot dispatch/combine
  einsums against a fixed per-expert capacity — no gather/scatter with
  data-dependent shapes, which XLA cannot tile. Tokens over capacity are
  dropped (their residual branch contributes zero), the standard
  Switch-style overflow semantics.
* **Experts as stacked params.** All experts live in single
  [E, d, h]/[E, h, d] tensors computed with einsums over the expert dim;
  under expert parallelism the params carry a ``P('expert', ...)``
  sharding (EP_RULES_MOE in parallel/sharding.py).
* **Explicit all-to-all dispatch under EP.** With ``ep_axis`` set, the
  expert computation runs in a shard_map: the TOKEN dim is split over
  the expert axis (GShard's groups — each shard routes its L/N tokens
  locally), ``lax.all_to_all`` exchanges the per-expert buffers so each
  shard holds ALL groups' tokens for its E/N resident experts, and a
  second all-to-all routes results back. Measured against leaving the
  einsums to GSPMD (which lowers this pattern to all-gathers + a
  combine all-reduce over the full [B, L, d] activations): the a2a
  pair moves ~2*k*C*d/N bytes per device vs ~3*B*L*d for the
  gather/reduce pattern — the difference between communication that
  SHRINKS with the expert axis and communication that does not.
* **Router in f32** (logits, softmax, and the load-balancing auxiliary
  loss) regardless of the activation dtype: top-k ties and the aux-loss
  gradients are precision-sensitive at bf16.

The auxiliary load-balancing loss is the Switch formulation
(mean over experts of fraction_dispatched * mean_router_prob, scaled by
E); consumers add ``aux_weight * aux_loss`` to their objective.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


from tensor2robot_tpu.parallel.sharding import constrain


def _capacity(k: int, tokens: int, factor: float, num_experts: int) -> int:
  """Per-expert slots for a token group: ceil(k*T*f/E), 8-aligned, <= T."""
  capacity = int(np.ceil(k * tokens * factor / num_experts))
  capacity = max(8, -(-capacity // 8) * 8)
  return min(capacity, tokens)


def _dispatch_combine(probs, expert_idx, num_experts: int, k: int,
                      capacity: int):
  """(dispatch, combine) one-hot tensors [B, T, E, C] for one token group.

  Position of each (token, choice) in its expert's buffer is the running
  count of earlier assignments to that expert (k-major cumsum order);
  tokens over capacity are dropped. Gates: k == 1 uses the RAW router
  probability (Switch semantics — renormalizing over a single kept
  choice would make the gate identically 1.0 and starve the router of
  task-loss gradient); k > 1 renormalizes over the kept subset.
  """
  b, t, e = probs.shape
  onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)   # [B, T, K, E]
  flat = onehot.transpose(0, 2, 1, 3).reshape(b, k * t, e)    # [B, KT, E]
  position = jnp.cumsum(flat, axis=1) - flat
  flat = flat * (position < capacity)
  pos_onehot = flat[..., None] * jax.nn.one_hot(
      position.astype(jnp.int32), capacity, dtype=jnp.float32)
  dispatch = pos_onehot.reshape(b, k, t, e, capacity).sum(1)  # [B, T, E, C]
  gate = dispatch.sum(-1) * probs                             # [B, T, E]
  if k > 1:
    gate = gate / jnp.maximum(gate.sum(-1, keepdims=True), 1e-9)
  combine = gate[..., None] * dispatch
  return dispatch, combine


class MoEMlp(nn.Module):
  """Top-k routed expert MLP: [B, L, d] -> [B, L, d] (+ aux loss).

  ``capacity_factor``: per-expert slots = ceil(k * T * factor / E),
  rounded up to a multiple of 8 (sublane alignment), where T is the
  routing GROUP size: the full L without expert parallelism, L/N per
  shard with it (GShard grouped dispatch — each group routes and drops
  independently). With ``capacity_factor >= E / k`` no token can
  overflow in either regime, making the two paths numerically identical
  (the parity tests' setting). Returns ``(out, aux_loss)``; aux_loss is
  the Switch load-balance term computed over ALL tokens.
  """

  num_experts: int
  expert_dim: int
  top_k: int = 2
  capacity_factor: float = 1.25
  mesh: Optional[object] = None
  ep_axis: Optional[str] = None
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    b, l, d = x.shape
    e, k = self.num_experts, min(self.top_k, self.num_experts)
    ep_size = 1
    if self.ep_axis and self.mesh is not None:
      if self.ep_axis not in self.mesh.shape:
        raise ValueError(
            'ep_axis {!r} is not an axis of the mesh (axes: {}); build the '
            'mesh with an expert axis (parallel.create_mesh).'.format(
                self.ep_axis, tuple(self.mesh.axis_names)))
      ep_size = int(self.mesh.shape[self.ep_axis])
      if e % ep_size:
        raise ValueError(
            'expert parallelism needs num_experts ({}) divisible by the '
            '{!r} axis size ({}).'.format(e, self.ep_axis, ep_size))
      if l % ep_size:
        raise ValueError(
            'expert parallelism routes tokens in L/N groups: the token '
            'dim ({}) must be divisible by the {!r} axis size ({}).'
            .format(l, self.ep_axis, ep_size))

    # Router (f32): probs over experts per token. Replicated math — GSPMD
    # shards it over whatever axes the activations carry.
    logits = nn.Dense(e, dtype=jnp.float32, name='router')(
        x.astype(jnp.float32))                              # [B, L, E]
    probs = jax.nn.softmax(logits, axis=-1)
    _, expert_idx = jax.lax.top_k(probs, k)                 # [B, L, K]

    w_in = self.param('w_in', nn.initializers.lecun_normal(),
                      (e, d, self.expert_dim), jnp.float32)
    w_out = self.param('w_out', nn.initializers.lecun_normal(),
                       (e, self.expert_dim, d), jnp.float32)

    if ep_size > 1:
      out = self._expert_parallel_apply(x, probs, expert_idx, w_in, w_out,
                                        e, k, ep_size)
    else:
      out = self._dense_apply(x, probs, expert_idx, w_in, w_out, e, k)

    # Switch load-balance loss: E * sum_e fraction_tokens_e * mean_prob_e
    # (uses the pre-capacity primary assignments, the standard estimator).
    primary = jax.nn.one_hot(expert_idx[..., 0], e, dtype=jnp.float32)
    fraction = primary.reshape(-1, e).mean(0)
    mean_prob = probs.reshape(-1, e).mean(0)
    aux_loss = e * jnp.sum(fraction * mean_prob)
    return out.astype(x.dtype), aux_loss

  def _dense_apply(self, x, probs, expert_idx, w_in, w_out, e, k):
    """Single-group dispatch: the whole L routes against global capacity."""
    capacity = _capacity(k, x.shape[1], self.capacity_factor, e)
    dispatch, combine = _dispatch_combine(probs, expert_idx, e, k, capacity)
    expert_in = jnp.einsum('blec,bld->ebcd', dispatch.astype(self.dtype),
                           x.astype(self.dtype))            # [E, B, C, d]
    h = nn.gelu(jnp.einsum('ebcd,edh->ebch', expert_in,
                           w_in.astype(self.dtype)))
    expert_out = jnp.einsum('ebch,ehd->ebcd', h,
                            w_out.astype(self.dtype))       # [E, B, C, d]
    return jnp.einsum('blec,ebcd->bld', combine.astype(self.dtype),
                      expert_out)

  def _expert_parallel_apply(self, x, probs, expert_idx, w_in, w_out,
                             e, k, ep_size):
    """GShard grouped dispatch in a shard_map: tokens split over the
    expert axis into N groups that route locally; ``lax.all_to_all``
    exchanges per-expert buffers so each shard computes its E/N resident
    experts over ALL groups' tokens, and a second all-to-all routes the
    results back (see module docstring for the measured byte comparison
    against leaving this pattern to GSPMD)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from tensor2robot_tpu.parallel.mesh import DATA_AXIS

    ep = self.ep_axis
    el = e // ep_size                                # local experts
    b, l, d = x.shape
    ls = l // ep_size                                # group (local) tokens
    capacity = _capacity(k, ls, self.capacity_factor, e)
    data_size = int(self.mesh.shape.get(DATA_AXIS, 1))
    batch_axis = (DATA_AXIS
                  if data_size > 1 and b % data_size == 0 else None)
    dtype = self.dtype

    def body(x_loc, probs_loc, idx_loc, w_in_loc, w_out_loc):
      # x_loc [b', Ls, d]; w_in_loc [El, d, h].
      dispatch, combine = _dispatch_combine(probs_loc, idx_loc, e, k,
                                            capacity)
      expert_in = jnp.einsum('blec,bld->ebcd', dispatch.astype(dtype),
                             x_loc.astype(dtype))    # [E, b', C, d]
      # Forward all-to-all: axis 0 (E = N*El, shard-contiguous expert
      # blocks) splits into N messages; received blocks stack source-
      # group-major -> [N, El, b', C, d] -> local experts over all groups.
      recv = jax.lax.all_to_all(expert_in, ep, split_axis=0,
                                concat_axis=0, tiled=True)
      bp = recv.shape[1]
      recv = recv.reshape(ep_size, el, bp, capacity, d)
      recv = recv.transpose(1, 2, 0, 3, 4).reshape(el, bp,
                                                   ep_size * capacity, d)
      h = nn.gelu(jnp.einsum('ebcd,edh->ebch', recv,
                             w_in_loc.astype(dtype)))
      out = jnp.einsum('ebch,ehd->ebcd', h, w_out_loc.astype(dtype))
      # Reverse all-to-all: regroup [El, b', N*C, d] by source group and
      # send each group its tokens back; received blocks stack
      # owner-shard-major, which IS global expert order (experts are
      # shard-contiguous) -> [E, b', C, d].
      out = out.reshape(el, bp, ep_size, capacity, d)
      out = out.transpose(2, 0, 1, 3, 4).reshape(ep_size * el, bp,
                                                 capacity, d)
      out = jax.lax.all_to_all(out, ep, split_axis=0, concat_axis=0,
                               tiled=True)           # [E, b', C, d]
      return jnp.einsum('blec,ebcd->bld', combine.astype(dtype), out)

    token_spec = P(batch_axis, ep, None)
    fn = shard_map(
        body, mesh=self.mesh,
        in_specs=(token_spec, token_spec, token_spec,
                  P(ep, None, None), P(ep, None, None)),
        out_specs=token_spec, check_rep=False)
    return fn(x, probs, expert_idx, w_in, w_out)
