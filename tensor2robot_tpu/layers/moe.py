"""Mixture-of-Experts MLP with expert parallelism over the mesh.

The reference has no MoE; this is part of the build-side mandate that
distributed training be first-class (SURVEY.md §5 build goals), filling
the 'ep' slot next to dp/fsdp/tp/sp. The design is the GShard/Switch
dispatch in its TPU-native form:

* **Static shapes everywhere.** Routing uses one-hot dispatch/combine
  einsums against a fixed per-expert capacity — no gather/scatter with
  data-dependent shapes, which XLA cannot tile. Tokens over capacity are
  dropped (their residual branch contributes zero), the standard
  Switch-style overflow semantics.
* **Experts as stacked params.** All experts live in single
  [E, d, h]/[E, h, d] tensors computed with einsums over the expert dim;
  under expert parallelism those params and the [E, C, d] dispatched
  activations carry a ``P('expert', ...)`` sharding
  (EP_RULES_MOE in parallel/sharding.py + the in-layer constraints) and
  GSPMD lowers the dispatch/combine einsums to all-to-alls over the
  'expert' axis — the MoE communication pattern, derived not hand-coded.
* **Router in f32** (logits, softmax, and the load-balancing auxiliary
  loss) regardless of the activation dtype: top-k ties and the aux-loss
  gradients are precision-sensitive at bf16.

The auxiliary load-balancing loss is the Switch formulation
(mean over experts of fraction_dispatched * mean_router_prob, scaled by
E); consumers add ``aux_weight * aux_loss`` to their objective.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


from tensor2robot_tpu.parallel.sharding import constrain


class MoEMlp(nn.Module):
  """Top-k routed expert MLP: [B, L, d] -> [B, L, d] (+ aux loss).

  ``capacity_factor``: per-expert slots = ceil(k * L * factor / E),
  rounded up to a multiple of 8 (sublane alignment). With
  ``capacity_factor >= E / k`` no token can overflow (useful in tests).
  Returns ``(out, aux_loss)``; aux_loss is the Switch load-balance term.
  """

  num_experts: int
  expert_dim: int
  top_k: int = 2
  capacity_factor: float = 1.25
  mesh: Optional[object] = None
  ep_axis: Optional[str] = None
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    b, l, d = x.shape
    e, k = self.num_experts, min(self.top_k, self.num_experts)
    if self.ep_axis and self.mesh is not None:
      if self.ep_axis not in self.mesh.shape:
        raise ValueError(
            'ep_axis {!r} is not an axis of the mesh (axes: {}); build the '
            'mesh with an expert axis (parallel.create_mesh).'.format(
                self.ep_axis, tuple(self.mesh.axis_names)))
      ep_size = int(self.mesh.shape[self.ep_axis])
      if e % ep_size:
        raise ValueError(
            'expert parallelism needs num_experts ({}) divisible by the '
            '{!r} axis size ({}).'.format(e, self.ep_axis, ep_size))
    capacity = int(np.ceil(k * l * self.capacity_factor / e))
    capacity = max(8, -(-capacity // 8) * 8)
    capacity = min(capacity, l)

    # Router (f32): probs over experts per token.
    logits = nn.Dense(e, dtype=jnp.float32, name='router')(
        x.astype(jnp.float32))                              # [B, L, E]
    probs = jax.nn.softmax(logits, axis=-1)

    # Top-k expert choice per token, then per-expert position assignment.
    _, expert_idx = jax.lax.top_k(probs, k)                 # [B, L, K]
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [B, L, K, E]
    # Position of each (token, choice) in its expert's buffer: the
    # running count of earlier assignments to that expert (k-major so a
    # token's secondary choice queues behind all primary choices of
    # earlier tokens at the same expert only via the cumsum order below).
    flat = onehot.transpose(0, 2, 1, 3).reshape(b, k * l, e)  # [B, KL, E]
    position = jnp.cumsum(flat, axis=1) - flat              # [B, KL, E]
    in_capacity = position < capacity
    flat = flat * in_capacity
    pos_onehot = flat[..., None] * jax.nn.one_hot(
        position.astype(jnp.int32), capacity,
        dtype=jnp.float32)                                  # [B, KL, E, C]
    dispatch = pos_onehot.reshape(b, k, l, e, capacity).sum(1)  # [B,L,E,C]

    # Gate values for surviving assignments, renormalized over kept k.
    gate = (dispatch.sum(-1) * probs)                       # [B, L, E]
    denom = jnp.maximum(gate.sum(-1, keepdims=True), 1e-9)
    combine = (gate / denom)[..., None] * dispatch          # [B, L, E, C]

    # Dispatch -> expert MLP -> combine, expert dim sharded over ep_axis.
    w_in = self.param('w_in', nn.initializers.lecun_normal(),
                      (e, d, self.expert_dim), jnp.float32)
    w_out = self.param('w_out', nn.initializers.lecun_normal(),
                       (e, self.expert_dim, d), jnp.float32)
    ep = self.ep_axis
    expert_in = jnp.einsum('blec,bld->ebcd', dispatch.astype(self.dtype),
                           x.astype(self.dtype))            # [E, B, C, d]
    from jax.sharding import PartitionSpec as P
    if ep:
      expert_in = constrain(expert_in, self.mesh, P(ep, None, None, None))
    h = jnp.einsum('ebcd,edh->ebch', expert_in,
                   w_in.astype(self.dtype))
    h = nn.gelu(h)
    expert_out = jnp.einsum('ebch,ehd->ebcd', h,
                            w_out.astype(self.dtype))       # [E, B, C, d]
    if ep:
      expert_out = constrain(expert_out, self.mesh, P(ep, None, None, None))
    out = jnp.einsum('blec,ebcd->bld', combine.astype(self.dtype),
                     expert_out)

    # Switch load-balance loss: E * sum_e fraction_tokens_e * mean_prob_e
    # (uses the pre-capacity primary assignments, the standard estimator).
    primary = jax.nn.one_hot(expert_idx[..., 0], e, dtype=jnp.float32)
    fraction = primary.reshape(-1, e).mean(0)
    mean_prob = probs.reshape(-1, e).mean(0)
    aux_loss = e * jnp.sum(fraction * mean_prob)
    return out.astype(x.dtype), aux_loss
