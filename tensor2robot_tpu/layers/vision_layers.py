"""Vision towers: image -> keypoint features -> pose heads, with FiLM.

Parity target: /root/reference/layers/vision_layers.py
(BuildImagesToFeaturesModel :34, BuildFILMParams :155, HighRes
multi-resolution variant :178, BuildImageFeaturesToPoseModel :270). slim
arg_scopes become explicit Flax modules; FiLM is applied pre-activation as
(1 + gamma) * h + beta; each conv follows the slim ordering
conv -> normalizer -> (FiLM) -> ReLU.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu.layers.pooling import max_pool
from tensor2robot_tpu.layers.spatial_softmax import spatial_softmax

_CHANNELS_PER_BLOCK = 32


def split_film_params(film_output_params: jnp.ndarray,
                      num_blocks: int
                      ) -> Tuple[Sequence[jnp.ndarray], Sequence[jnp.ndarray]]:
  """[batch, 2*num_blocks*C] -> per-block broadcastable (1+gamma), beta."""
  expected = 2 * num_blocks * _CHANNELS_PER_BLOCK
  if film_output_params.ndim != 2 or film_output_params.shape[-1] != expected:
    raise ValueError(
        'FiLM params must be [batch, {}]; got {}.'.format(
            expected, film_output_params.shape))
  reshaped = film_output_params[:, None, None, :]
  chunks = jnp.split(reshaped, 2 * num_blocks, axis=-1)
  gammas = [1.0 + g for g in chunks[:num_blocks]]
  betas = chunks[num_blocks:]
  return gammas, betas


class ImagesToFeaturesNet(nn.Module):
  """Conv tower + spatial softmax -> expected keypoints (ref :34).

  Returns (expected_feature_points [B, 2*num_output_maps],
  {'softmax': maps}).
  """

  filter_size: int = 3
  num_blocks: int = 5
  num_output_maps: int = 32
  use_batch_norm: bool = False   # reference defaults to layer norm
  stride2_blocks: Sequence[int] = (0, 1)

  @nn.compact
  def __call__(self, images: jnp.ndarray,
               film_output_params: Optional[jnp.ndarray] = None,
               train: bool = False):
    gammas = betas = None
    if film_output_params is not None:
      gammas, betas = split_film_params(film_output_params, self.num_blocks)
    net = images
    for i in range(self.num_blocks):
      stride = 2 if i in self.stride2_blocks else 1
      net = nn.Conv(
          features=_CHANNELS_PER_BLOCK,
          kernel_size=(self.filter_size, self.filter_size),
          strides=(stride, stride),
          padding='VALID',
          bias_init=nn.initializers.constant(0.01),
          kernel_init=nn.initializers.xavier_uniform(),
          name='conv{:d}'.format(i + 2))(net)
      net = self._normalize(net, train, scale=False,
                            name='norm{:d}'.format(i + 2))
      if gammas is not None:
        net = gammas[i] * net + betas[i]
      net = nn.relu(net)
    net = nn.Conv(
        features=self.num_output_maps, kernel_size=(1, 1), padding='VALID',
        bias_init=nn.initializers.constant(0.01),
        kernel_init=nn.initializers.xavier_uniform(),
        name='final_conv_1x1')(net)
    net = self._normalize(net, train, scale=True, name='final_norm')
    net = nn.relu(net)
    expected_points, softmax_maps = spatial_softmax(net)
    return expected_points, {'softmax': softmax_maps}

  def _normalize(self, net, train, scale, name):
    if self.use_batch_norm:
      return nn.BatchNorm(
          use_running_average=not train, momentum=0.99, epsilon=1e-4,
          use_scale=scale, name=name)(net)
    return nn.LayerNorm(use_scale=scale, name=name)(net)


class ImagesToFeaturesHighResNet(nn.Module):
  """Multi-resolution feature-sum tower (ref :178, PI-GPS arch).

  Per-block 1x1 taps are nearest-resized up to the first tap's resolution
  and summed; the spatial softmax runs at that highest resolution.
  """

  filter_size: int = 3
  num_blocks: int = 5
  num_output_maps: int = 32
  use_batch_norm: bool = True    # reference HighRes defaults to batch norm

  @nn.compact
  def __call__(self, images: jnp.ndarray, train: bool = False):
    def conv(features, kernel, stride, name):
      return nn.Conv(
          features=features, kernel_size=(kernel, kernel),
          strides=(stride, stride), padding='VALID',
          kernel_init=nn.initializers.truncated_normal(stddev=0.1),
          name=name)

    def norm_relu(net, scale, name):
      if self.use_batch_norm:
        net = nn.BatchNorm(use_running_average=not train, momentum=0.99,
                           epsilon=1e-4, use_scale=scale, name=name)(net)
      else:
        net = nn.LayerNorm(use_scale=scale, name=name)(net)
      return nn.relu(net)

    block_outs = []
    net = nn.avg_pool(images, (2, 2), strides=(2, 2), padding='VALID')
    net = conv(16, self.filter_size, 2, 'conv1')(net)
    net = norm_relu(net, False, 'norm1')
    net = conv(32, self.filter_size, 1, 'conv2')(net)
    net = norm_relu(net, False, 'norm2')
    tap = conv(32, 1, 1, 'conv2_1x1')(net)
    block_outs.append(norm_relu(tap, False, 'norm2_1x1'))
    for i in range(1, self.num_blocks):
      net = max_pool(net, (2, 2), strides=(2, 2), padding='VALID')
      net = conv(32, self.filter_size, 1, 'conv{:d}'.format(i + 2))(net)
      net = norm_relu(net, False, 'norm{:d}'.format(i + 2))
      tap = conv(32, 1, 1, 'conv{:d}_1x1'.format(i + 2))(net)
      block_outs.append(norm_relu(tap, False, 'norm{:d}_1x1'.format(i + 2)))
    target_hw = block_outs[0].shape[1:3]
    resized = [
        jax.image.resize(
            layer, layer.shape[:1] + target_hw + layer.shape[3:],
            method='nearest') for layer in block_outs
    ]
    net = sum(resized)
    net = conv(self.num_output_maps, 1, 1, 'final_conv_1x1')(net)
    net = norm_relu(net, True, 'final_norm')
    expected_points, softmax_maps = spatial_softmax(net)
    return expected_points, {'softmax': softmax_maps}


class FilmParams(nn.Module):
  """Linear FiLM generator (ref BuildFILMParams :155)."""

  film_output_size: int = 2 * 5 * _CHANNELS_PER_BLOCK

  @nn.compact
  def __call__(self, embedding: jnp.ndarray) -> jnp.ndarray:
    return nn.Dense(self.film_output_size, name='film')(embedding)


class ImageFeaturesToPoseNet(nn.Module):
  """Feature points (+ aux input) -> pose vector (ref :270).

  With ``aux_output_dim > 0`` returns (pose, aux_prediction); with
  ``num_outputs is None`` returns the last hidden layer.
  """

  num_outputs: Optional[int] = 7
  fc_layers: Sequence[int] = (100, 100)
  bias_transform_size: int = 10
  aux_output_dim: int = 0

  @nn.compact
  def __call__(self, feature_points: jnp.ndarray,
               aux_input: Optional[jnp.ndarray] = None):
    net = feature_points
    if aux_input is not None:
      net = jnp.concatenate([net, aux_input], axis=-1)
    # Bias transform: a learned constant concatenated to the features
    # (helps MAML adapt biases; ref :270's bias_transform).
    if self.bias_transform_size:
      bias = self.param('bias_transform', nn.initializers.zeros,
                        (self.bias_transform_size,), jnp.float32)
      tiled = jnp.broadcast_to(
          bias.astype(net.dtype),
          net.shape[:-1] + (self.bias_transform_size,))
      net = jnp.concatenate([net, tiled], axis=-1)
    for width in self.fc_layers:
      net = nn.Dense(width)(net)
      net = nn.LayerNorm()(net)
      net = nn.relu(net)
    output = net if self.num_outputs is None else nn.Dense(
        self.num_outputs)(net)
    if self.aux_output_dim:
      return output, nn.Dense(self.aux_output_dim, name='aux_dense')(net)
    return output
