"""TPU-fast max pooling with an index-based backward pass.

``jax.grad`` of the standard reduce-window max pool lowers to XLA
``select-and-scatter``, which the TPU executes an order of magnitude
slower than the surrounding convolutions (measured 6.7 ms for the
79x79x64 pool backward of the QT-Opt critic at batch 256 — as long as a
5x5 conv forward on the same tensor). For NON-OVERLAPPING pools
(window == strides, the only kind the Grasping44/vision stacks use) the
backward pass is just "route the cotangent to the window argmax".

The implementation is deliberately transpose-free — every reshape below
is contiguous, and the window dims are reduced with strided reductions
(which the TPU handles natively); an earlier variant that flattened the
window with a [B, Ho, wh, Wo, ww, C] transpose spent more time in the
relayout copies than select-and-scatter cost in the first place:

  forward:  pad (SAME) or crop (VALID) to a window multiple, then
            max + argmax over the H-window dim, reshape, max + argmax
            over the W-window dim; save the two int8 index grids.
  backward: two nested one-hot compares against the saved indices
            route dy back to the selected cell; un-pad/crop.

Tie-breaking: the gradient goes to one maximal cell, chosen stage-wise
(first maximal row within each window column, then first maximal
column). XLA's select-and-scatter picks the row-major first maximal
cell — the two can differ ONLY when two distinct cells of one window
tie bit-exactly, in which case which tied cell receives the gradient is
immaterial to training (and unspecified across TF kernels anyway).

``max_pool`` is a drop-in for ``flax.linen.max_pool`` and silently falls
back to it for overlapping windows (e.g. the ResNet stem's 3x3/2).
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def _neg_inf(dtype) -> jnp.ndarray:
  if jnp.issubdtype(dtype, jnp.floating):
    return jnp.array(-jnp.inf, dtype)
  return jnp.array(jnp.iinfo(dtype).min, dtype)


def _geometry(size: int, window: int, padding: str) -> Tuple[int, int, int]:
  """Returns (out, pad_lo, pad_hi) for one dim; pad_hi < 0 means crop."""
  if padding == 'VALID':
    out = size // window
    return out, 0, out * window - size  # <= 0: crop the tail
  out = -(-size // window)  # SAME: ceil
  total = out * window - size
  return out, total // 2, total - total // 2


def _pad_or_crop(x, window, padding):
  b, h, w, c = x.shape
  wh, ww = window
  ho, plh, phh = _geometry(h, wh, padding)
  wo, plw, phw = _geometry(w, ww, padding)
  if phh < 0 or phw < 0:  # VALID: drop the tail that fits no full window
    x = x[:, :ho * wh, :wo * ww, :]
  elif plh or phh or plw or phw:
    x = jnp.pad(x, ((0, 0), (plh, phh), (plw, phw), (0, 0)),
                constant_values=_neg_inf(x.dtype))
  return x, (ho, wo), (plh, phh, plw, phw)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _max_pool_nonoverlap(x, window, padding):
  xp, (ho, wo), _ = _pad_or_crop(x, window, padding)
  b, _, _, c = x.shape
  wh, ww = window
  m1 = xp.reshape(b, ho, wh, wo * ww, c).max(axis=2)
  return m1.reshape(b, ho, wo, ww, c).max(axis=3)


def _max_pool_fwd(x, window, padding):
  xp, (ho, wo), pads = _pad_or_crop(x, window, padding)
  b, _, _, c = x.shape
  wh, ww = window
  xr = xp.reshape(b, ho, wh, wo * ww, c)
  m1 = xr.max(axis=2)
  i1 = xr.argmax(axis=2).astype(jnp.int8)       # [B, Ho, Wo*ww, C]
  m1r = m1.reshape(b, ho, wo, ww, c)
  out = m1r.max(axis=3)
  i2 = m1r.argmax(axis=3).astype(jnp.int8)      # [B, Ho, Wo, C]
  return out, (i1, i2, pads, x.shape)


def _max_pool_bwd(window, padding, res, dy):
  i1, i2, (plh, phh, plw, phw), x_shape = res
  b, h, w, c = x_shape
  wh, ww = window
  ho, wo = i2.shape[1], i2.shape[2]
  iota_w = jnp.arange(ww, dtype=jnp.int8).reshape(1, 1, 1, ww, 1)
  d1 = jnp.where(i2[:, :, :, None, :] == iota_w, dy[:, :, :, None, :],
                 jnp.zeros((), dy.dtype))      # [B, Ho, Wo, ww, C]
  d1 = d1.reshape(b, ho, 1, wo * ww, c)
  iota_h = jnp.arange(wh, dtype=jnp.int8).reshape(1, 1, wh, 1, 1)
  dx = jnp.where(i1[:, :, None, :, :] == iota_h, d1,
                 jnp.zeros((), dy.dtype))      # [B, Ho, wh, Wo*ww, C]
  dx = dx.reshape(b, ho * wh, wo * ww, c)
  if phh < 0 or phw < 0:  # VALID crop: zero-fill the dropped tail
    dx = jnp.pad(dx, ((0, 0), (0, h - ho * wh), (0, w - wo * ww), (0, 0)))
  else:
    dx = dx[:, plh:plh + h, plw:plw + w, :]
  return (dx,)


_max_pool_nonoverlap.defvjp(_max_pool_fwd, _max_pool_bwd)


# Above this many elements PER IMAGE (H*W*C — the crossover is a spatial
# property; both paths scale linearly in batch) the index path's
# materialized intermediates (padded copy, index grids, one-hot
# broadcasts) cost more HBM traffic than select-and-scatter itself;
# measured on a v5e with the QT-Opt maps: 79x79x64 (400k) wins 4x,
# 236x236x64 (3.6M) loses 2x.
_INDEX_PATH_MAX_ELEMENTS_PER_IMAGE = 1_000_000


def max_pool(x: jnp.ndarray, window_shape: Sequence[int],
             strides: Sequence[int], padding: str = 'VALID') -> jnp.ndarray:
  """Drop-in ``nn.max_pool`` with a TPU-fast backward for window==strides.

  Caveat: the fast path is a ``custom_vjp``, so forward-mode autodiff
  (``jax.jvp`` / ``jacfwd`` / ``hessian``) cannot differentiate through
  it — reverse mode (``grad`` / ``vjp``), as used by every trainer in
  this framework, is fully supported. Forward-mode callers get the
  reduce-window fallback by calling ``flax.linen.max_pool`` directly.
  """
  window_shape = tuple(window_shape)
  if strides is None:
    # flax's default (None == stride 1): overlapping by construction, so
    # the fast path never applies — defer entirely to nn.max_pool.
    return nn.max_pool(x, window_shape, strides=None, padding=padding)
  strides = tuple(strides)
  per_image = 1
  for d in x.shape[1:]:
    per_image *= d
  if (window_shape == strides and len(window_shape) == 2 and x.ndim == 4 and
      padding in ('SAME', 'VALID') and
      max(window_shape) <= 127 and  # index grids are int8
      per_image <= _INDEX_PATH_MAX_ELEMENTS_PER_IMAGE):
    return _max_pool_nonoverlap(x, window_shape, padding)
  return nn.max_pool(x, window_shape, strides=strides, padding=padding)
