"""Pallas TPU kernel: non-overlapping max pool with index-routed backward.

STATUS — correct, NOT wired into the hot path. This was the SURVEY §7
"Pallas where the profile says so" investigation for the QT-Opt stem
pool (236x236x64, the one map too large for pooling.py's XLA index
path). Measured verdict on v5e at batch 256, fwd+bwd per step:
reduce-window + select-and-scatter 20.2 ms, this kernel 37.3 ms —
**XLA wins**. The op is VPU-bound, and the formulations Mosaic accepts
force ~3x redundant element work: no strided sublane slices and no
sublane-splitting reshapes exist, so the column stage must compute
max/argmax at EVERY column position (stride-1 shifted slices) and then
downsample via a 0/1 selection-matrix matmul; bf16 vector compares are
unsupported, forcing f32 staging (2x the VPU traffic); i1-select
relayouts are rejected, forcing arithmetic selects (extra multiplies).
The kernel stays as the measured record of that finding (documented in
docs/performance.md), with interpret-mode parity tests in
tests/test_layers.py pinning its numerics.

  forward:  per (batch, row-band) tile: row-stage strictly-greater
            max/argmax chain, all-positions column stage, matmul
            downsample; writes pooled map + int8 window-index grid.
  backward: matmul-upsamples (idx, dy) to column resolution, routes dy
            by in-window position match, leading-dim stacks the wh row
            contributions, writes the dx tile once.

Tie rule: first maximal element stage-wise (rows within a column, then
columns) — identical to pooling.py's XLA index path; differs from
select-and-scatter only on bit-exact ties, where the routed cell choice
is immaterial (gradient mass is conserved either way).

Geometry: window == strides (non-overlapping), NHWC, and zero LOW
padding in both spatial dims — i.e. SAME with at most one padded
row/column at the high end (236->79 has exactly that) or VALID.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Output rows computed per program instance; 8 keeps the input tile
# (R*wh rows x W x C) under ~1 MB for the 236x236x64 target shape.
_BLOCK_OUT_ROWS = 8


def supported(x_shape: Tuple[int, ...], window: Tuple[int, int],
              padding: str) -> bool:
  """True if (shape, window, padding) fits this kernel's geometry."""
  if len(x_shape) != 4:
    return False
  _, h, w, _ = x_shape
  wh, ww = window
  if wh * ww > 127:  # combined window index is stored int8
    return False
  if padding == 'VALID':
    return h >= wh and w >= ww
  if padding != 'SAME':
    return False
  # SAME with stride == window pads (out*win - size) split low/high with
  # low = total // 2; zero low padding means total pad <= 1 per dim.
  return (-h) % wh <= 1 and (-w) % ww <= 1


def _geometry(size: int, window: int, padding: str) -> int:
  if padding == 'VALID':
    return size // window
  return -(-size // window)


def _fwd_kernel(x_ref, out_ref, idx_ref, *, R, wh, ww, H, W, C, Ho, Wo):
  band = pl.program_id(1)
  # All staging in f32: the v5e VPU has no native bf16 compare, and the
  # tiles are small enough (~1.5 MB at the 236x236x64 target) that the
  # wider compute dtype is free.
  x = x_ref[0].astype(jnp.float32)                 # [R*wh, W, C]
  if Ho * wh > H:  # SAME high-pad row: mask rows past the input edge
    row = (jax.lax.broadcasted_iota(jnp.int32, (R * wh, W, C), 0) +
           band * R * wh)
    # Rows past the edge are out-of-bounds block reads whose VMEM
    # content is arbitrary stale bits (possibly NaN/Inf, which no
    # multiply-by-zero scrub survives) — select them away in f32, where
    # Mosaic's i1-select lowering works (the bf16 one is rejected).
    x = jnp.where(row < H, x, jnp.asarray(-1e30, x.dtype))
  xr = x.reshape(R, wh, W, C)                      # leading split: OK
  # Row stage: strictly-greater chain keeps the first maximal row.
  m1 = xr[:, 0]
  i1 = jnp.zeros((R, W, C), jnp.int32)
  for r in range(1, wh):
    take = (xr[:, r] > m1).astype(jnp.int32)
    m1 = jnp.maximum(m1, xr[:, r])
    i1 = i1 * (1 - take) + r * take

  wo_main = W // ww
  tail = W - wo_main * ww                          # SAME: 0 or 1..ww-1
  span = (wo_main - 1) * ww + 1

  # Column stage, Mosaic-style: no strided sublane slices and no
  # sublane-splitting reshapes exist, so compute the window max/argmax
  # at EVERY column position with stride-1 shifted slices, then
  # downsample (take every ww-th sublane) with a 0/1 selection-matrix
  # matmul — a single-nonzero-per-row matmul copies values exactly.
  mo_all = m1[:, :span]
  io_all = jnp.zeros((R, span, C), jnp.int32)
  for j in range(1, ww):
    wc = m1[:, j:span + j]
    take = (wc > mo_all).astype(jnp.int32)
    mo_all = jnp.maximum(mo_all, wc)
    io_all = io_all * (1 - take) + j * take
  sel_all = i1[:, :span]
  for j in range(1, ww):
    eq = (io_all == j).astype(jnp.int32)
    sel_all = sel_all * (1 - eq) + i1[:, j:span + j] * eq
  k_all = sel_all * ww + io_all                    # [R, span, C]

  # select[w, o] = 1 iff w == o*ww  (span x wo_main)
  wpos = jax.lax.broadcasted_iota(jnp.int32, (span, wo_main), 0)
  opos = jax.lax.broadcasted_iota(jnp.int32, (span, wo_main), 1)
  select = (wpos == opos * ww).astype(jnp.float32)

  def downsample(a):                 # [R, span, C] -> [R, wo_main, C]
    # HIGHEST precision: the default TPU matmul precision rounds f32
    # operands to bf16, breaking the exact-copy invariant of the 0/1
    # selection matmul.
    d = jax.lax.dot_general(a.astype(jnp.float32), select,
                            (((1,), (0,)), ((), ())),
                            precision=jax.lax.Precision.HIGHEST,
                            preferred_element_type=jnp.float32)
    return jnp.swapaxes(d, 1, 2)

  out_ref[0, :, :wo_main, :] = downsample(mo_all).astype(out_ref.dtype)
  idx_ref[0, :, :wo_main, :] = downsample(k_all).astype(jnp.int8)

  if tail and Wo > wo_main:  # SAME: partial high-edge window (VALID
    # crops the leftover instead — Wo == wo_main there, and storing a
    # tail would clamp onto the last valid column)
    mt = m1[:, wo_main * ww]
    it = jnp.zeros((R, C), jnp.int32)
    for j in range(1, tail):
      wc = m1[:, wo_main * ww + j]
      take = (wc > mt).astype(jnp.int32)
      mt = jnp.maximum(mt, wc)
      it = it * (1 - take) + j * take
    selt = i1[:, wo_main * ww]
    for j in range(1, tail):
      eq = (it == j).astype(jnp.int32)
      selt = selt * (1 - eq) + i1[:, wo_main * ww + j] * eq
    out_ref[0, :, wo_main, :] = mt.astype(out_ref.dtype)
    idx_ref[0, :, wo_main, :] = (selt * ww + it).astype(jnp.int8)


def _bwd_kernel(idx_ref, dy_ref, dx_ref, *, R, wh, ww, H, W, C, Ho, Wo):
  band = pl.program_id(1)
  k = idx_ref[0].astype(jnp.int32)                 # [R, Wo, C]
  dy = dy_ref[0].astype(jnp.float32)
  # Mask output rows past Ho (the last band may overrun the output).
  orow = jax.lax.broadcasted_iota(jnp.int32, (R, Wo, C), 0) + band * R
  dy = dy * (orow < Ho).astype(dy.dtype)

  # Upsample window index + cotangent to input-column resolution
  # (up[w] = v[w // ww] — the window->column map, exact since low
  # padding is zero) with a 0/1 selection-matrix matmul, the transpose
  # of the forward's downsample. A single-nonzero-per-row matmul copies
  # values exactly; int indices survive the f32 accumulate unchanged.
  wmain = min(Wo * ww, W)  # < W only for VALID non-divisible widths
  opos = jax.lax.broadcasted_iota(jnp.int32, (Wo, wmain), 0)
  wpos = jax.lax.broadcasted_iota(jnp.int32, (Wo, wmain), 1)
  spread = (opos == wpos // ww).astype(jnp.float32)

  def upsample(a):                 # [R, Wo, C] -> [R, wmain, C]
    # HIGHEST precision for the same exact-copy reason as the forward.
    d = jax.lax.dot_general(a.astype(jnp.float32), spread,
                            (((1,), (0,)), ((), ())),
                            precision=jax.lax.Precision.HIGHEST,
                            preferred_element_type=jnp.float32)
    return jnp.swapaxes(d, 1, 2)   # [R, C, wmain] -> [R, wmain, C]

  k_up = upsample(k).astype(jnp.int32)
  dy_up = upsample(dy).astype(dy.dtype)
  col = jax.lax.broadcasted_iota(jnp.int32, (R, wmain, C), 1) % ww
  contrib = dy_up * (k_up % ww == col).astype(dy.dtype)

  r_up = k_up // ww
  rows = [(contrib * (r_up == dr).astype(dy.dtype))[:, None]
          for dr in range(wh)]
  # [R, wh, wmain, C] (leading-dim stack) -> [R*wh, wmain, C] (leading
  # merge), then zero-fill any VALID-cropped leftover columns.
  dx_ref[0, :, :wmain, :] = jnp.concatenate(rows, axis=1).reshape(
      R * wh, wmain, C).astype(dx_ref.dtype)
  for j in range(W - wmain):
    dx_ref[0, :, wmain + j, :] = jnp.zeros((R * wh, C), dx_ref.dtype)


def _pallas_call_fwd(x, window, padding, interpret):
  b, h, w, ch = x.shape
  wh, ww = window
  ho, wo = _geometry(h, wh, padding), _geometry(w, ww, padding)
  nb = -(-ho // _BLOCK_OUT_ROWS)
  kernel = functools.partial(_fwd_kernel, R=_BLOCK_OUT_ROWS, wh=wh, ww=ww,
                             H=h, W=w, C=ch, Ho=ho, Wo=wo)
  return pl.pallas_call(
      kernel,
      grid=(b, nb),
      in_specs=[pl.BlockSpec((1, _BLOCK_OUT_ROWS * wh, w, ch),
                             lambda b, i: (b, i, 0, 0),
                             memory_space=pltpu.VMEM)],
      out_specs=[
          pl.BlockSpec((1, _BLOCK_OUT_ROWS, wo, ch),
                       lambda b, i: (b, i, 0, 0), memory_space=pltpu.VMEM),
          pl.BlockSpec((1, _BLOCK_OUT_ROWS, wo, ch),
                       lambda b, i: (b, i, 0, 0), memory_space=pltpu.VMEM),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((b, ho, wo, ch), x.dtype),
          jax.ShapeDtypeStruct((b, ho, wo, ch), jnp.int8),
      ],
      interpret=interpret,
  )(x)


def _pallas_call_bwd(idx, dy, x_shape, window, padding, interpret):
  b, h, w, ch = x_shape
  wh, ww = window
  ho, wo = idx.shape[1], idx.shape[2]
  nb = -(-ho // _BLOCK_OUT_ROWS)
  kernel = functools.partial(_bwd_kernel, R=_BLOCK_OUT_ROWS, wh=wh, ww=ww,
                             H=h, W=w, C=ch, Ho=ho, Wo=wo)
  return pl.pallas_call(
      kernel,
      grid=(b, nb),
      in_specs=[
          pl.BlockSpec((1, _BLOCK_OUT_ROWS, wo, ch),
                       lambda b, i: (b, i, 0, 0), memory_space=pltpu.VMEM),
          pl.BlockSpec((1, _BLOCK_OUT_ROWS, wo, ch),
                       lambda b, i: (b, i, 0, 0), memory_space=pltpu.VMEM),
      ],
      out_specs=pl.BlockSpec((1, _BLOCK_OUT_ROWS * wh, w, ch),
                             lambda b, i: (b, i, 0, 0),
                             memory_space=pltpu.VMEM),
      out_shape=jax.ShapeDtypeStruct((b, h, w, ch), dy.dtype),
      interpret=interpret,
  )(idx, dy)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def max_pool_pallas(x, window, padding='SAME', interpret=False):
  """Non-overlapping max pool; see module docstring for the geometry."""
  out, _ = _pallas_call_fwd(x, window, padding, interpret)
  return out


def _vjp_fwd(x, window, padding, interpret):
  out, idx = _pallas_call_fwd(x, window, padding, interpret)
  return out, (idx, x.shape)


def _vjp_bwd(window, padding, interpret, res, dy):
  idx, x_shape = res
  return (_pallas_call_bwd(idx, dy, x_shape, window, padding, interpret),)


max_pool_pallas.defvjp(_vjp_fwd, _vjp_bwd)
