"""NN layer library: vision towers, FiLM-ResNet, MDN, SNAIL, TEC."""

from tensor2robot_tpu.layers import mdn
from tensor2robot_tpu.layers import resnet
from tensor2robot_tpu.layers import snail
from tensor2robot_tpu.layers import tec
from tensor2robot_tpu.layers import vision_layers
from tensor2robot_tpu.layers.spatial_softmax import spatial_softmax

__all__ = ['mdn', 'resnet', 'snail', 'spatial_softmax', 'tec',
           'vision_layers']
