"""Transformer layers for sequence-to-action policies (RT-1-style).

The reference's temporal models stop at causal TCNs and dot-product
attention over tiny windows (SNAIL, /root/reference/layers/snail.py:78;
TEC, /root/reference/layers/tec.py:91). This module is the long-context
successor those layers never got: a causal transformer over per-frame
visual tokens whose attention backend scales from a single chip to a
sequence-sharded device mesh:

  * ``attention_mode='xla'``   — dense einsum attention (oracle; small L).
  * ``attention_mode='flash'`` — the Pallas blockwise kernel
    (parallel/flash_attention.py): O(L) memory, Pallas forward AND
    backward; measured numbers live in docs/performance.md (fwd ~3.8x
    XLA at L=16k; trains at L=32k where dense attention OOMs on a v5e).
  * ``attention_mode='ring'``  — ring attention over the mesh's sequence
    axis (parallel/ring_attention.py): O(L/N) per-device memory with k/v
    blocks rotating over ICI; trainable via its blockwise-recompute VJP.
  * ``attention_mode='auto'``  — dense below _FLASH_MIN_LENGTH, flash
    above (and on CPU backends, always dense — the kernel would run in
    the slow interpreter).

Causality is at TOKEN granularity: tokens are ordered frame-major, so a
frame's tokens attend to all earlier frames' tokens and to predecessors
within their own frame. This is slightly stricter than RT-1's frame-block
mask (which lets a frame's tokens also see later tokens of the same
frame) and equally leak-free; it lets all three backends share the plain
causal mask the kernels implement.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# importlib: the parallel package re-exports the flash_attention FUNCTION
# under the same name as its module, which shadows plain module imports.
import importlib

flash_lib = importlib.import_module(
    'tensor2robot_tpu.parallel.flash_attention')
ring_lib = importlib.import_module(
    'tensor2robot_tpu.parallel.ring_attention')

from tensor2robot_tpu.parallel.sharding import constrain as _constrain

_FLASH_MIN_LENGTH = 2048


def scaled_dot_attention(q, k, v, causal: bool) -> jnp.ndarray:
  """Dense [B, L, H, D] attention in f32 accumulation (the oracle path)."""
  scale = 1.0 / np.sqrt(q.shape[-1])
  scores = jnp.einsum('bqhd,bkhd->bhqk', q.astype(jnp.float32),
                      k.astype(jnp.float32)) * scale
  if causal:
    l_q, l_k = q.shape[1], k.shape[1]
    mask = jnp.tril(jnp.ones((l_q, l_k), bool), k=l_k - l_q)
    scores = jnp.where(mask, scores, -jnp.inf)
  probs = jax.nn.softmax(scores, axis=-1)
  return jnp.einsum('bhqk,bkhd->bqhd', probs, v.astype(jnp.float32)
                    ).astype(q.dtype)


def resolve_attention_mode(mode: str, seq_length: int) -> str:
  """'auto' -> 'flash'/'xla' by backend and length; other modes pass through.

  Lengths with poor block divisibility fall back to dense rather than
  running the kernel with tiny blocks (the kernel itself steps its blocks
  down to dividing sizes, so explicit 'flash' always works — 'auto' just
  avoids the slow small-block regime).
  """
  if mode != 'auto':
    return mode
  on_tpu = jax.default_backend() == 'tpu'
  return 'flash' if (on_tpu and seq_length >= _FLASH_MIN_LENGTH
                     and seq_length % 128 == 0) else 'xla'


def run_attention(q, k, v, *, mode: str, causal: bool,
                  mesh=None, seq_axis: str = 'data') -> jnp.ndarray:
  """Dispatches [B, L, H, D] self-attention to the selected backend."""
  mode = resolve_attention_mode(mode, q.shape[1])
  if mode == 'xla':
    return scaled_dot_attention(q, k, v, causal)
  if mode == 'flash':
    return flash_lib.flash_attention(q, k, v, causal=causal)
  if mode == 'ring':
    if mesh is None:
      raise ValueError("attention_mode='ring' requires a mesh.")
    return ring_lib.ring_self_attention(q, k, v, mesh, seq_axis=seq_axis,
                                        causal=causal)
  raise ValueError('Unknown attention mode: {!r}'.format(mode))


class MultiHeadAttention(nn.Module):
  """Self-attention with pluggable backend (see module docstring).

  ``tp_axis``: Megatron-style tensor parallelism. The qkv projection is
  laid out HEAD-MAJOR (columns grouped [H, 3, Dh]) so sharding its output
  dim over ``tp_axis`` (parallel/sharding.py TP_RULES_TRANSFORMER) splits
  whole heads per device; attention then computes only local heads, and
  the out projection's input-dim sharding leaves a partial sum that XLA
  closes with one psum over the axis. With ``attention_mode='flash'`` the
  Pallas kernel is wrapped in a shard_map over ``tp_axis`` — attention is
  head-independent, so each device runs the kernel on its resident heads
  (a pallas_call is opaque to GSPMD and would otherwise be all-gathered).
  """

  num_heads: int
  head_dim: int
  attention_mode: str = 'auto'
  causal: bool = True
  mesh: Optional[object] = None  # jax.sharding.Mesh for 'ring'/tp
  seq_axis: str = 'data'
  tp_axis: Optional[str] = None
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    from jax.sharding import PartitionSpec as P

    b, l, _ = x.shape
    features = self.num_heads * self.head_dim
    if self.tp_axis and self.mesh is not None:
      if self.tp_axis not in self.mesh.shape:
        # Mirror MoEMlp's ep_axis check: a missing axis would otherwise
        # skip the divisibility check here and surface later as a cryptic
        # with_sharding_constraint error.
        raise ValueError(
            'tp_axis {!r} is not an axis of the mesh (axes: {}); build the '
            'mesh with a model axis (parallel.create_mesh).'.format(
                self.tp_axis, tuple(self.mesh.axis_names)))
      tp_size = int(self.mesh.shape[self.tp_axis])
      if self.num_heads % tp_size:
        # Catch at trace time: the param rule would otherwise shard the
        # flat qkv column dim mid-head (parallel/sharding.py matches on
        # divisibility of H*3*Dh, which it cannot decompose into heads).
        raise ValueError(
            'tensor parallelism needs num_heads ({}) divisible by the '
            '{!r} axis size ({}).'.format(self.num_heads, self.tp_axis,
                                          tp_size))
    # Head-major qkv columns: [d, H*3*Dh] (NOT q|k|v-major) — see class
    # docstring; single-chip numerics only permute init columns. NOTE:
    # checkpoints saved before round 4's head-major change load
    # shape-compatibly but are scrambled — re-train (none shipped).
    qkv = nn.Dense(3 * features, dtype=self.dtype, name='qkv')(x)
    qkv = qkv.reshape(b, l, self.num_heads, 3, self.head_dim)
    if self.tp_axis:
      qkv = _constrain(qkv, self.mesh, P(None, None, self.tp_axis, None, None))
    q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    # Resolve 'auto' BEFORE the tp/flash routing below — otherwise
    # run_attention would resolve it internally and the opaque
    # pallas_call would be all-gathered over the model axis.
    mode = resolve_attention_mode(self.attention_mode, l)
    if self.tp_axis and mode == 'ring':
      # Only the flash path is shard_mapped over tp; the ring path's
      # seq-axis shard_map would force the head-sharded q/k/v to be
      # all-gathered over the model axis, silently negating tensor
      # parallelism for attention. Reject like the pipeline path does.
      raise ValueError(
          "tp_axis cannot combine with attention_mode='ring': the ring "
          'shard_map replicates over the model axis, all-gathering the '
          "head-sharded q/k/v. Use 'flash' (head-resident shard_map) or "
          "'xla' with tensor parallelism, or drop tp_axis for ring.")
    if self.tp_axis and mode == 'flash':
      out = _flash_sharded_heads(q, k, v, causal=self.causal, mesh=self.mesh,
                                 tp_axis=self.tp_axis)
    else:
      out = run_attention(q, k, v, mode=mode, causal=self.causal,
                          mesh=self.mesh, seq_axis=self.seq_axis)
    if self.tp_axis:
      out = _constrain(out, self.mesh, P(None, None, self.tp_axis, None))
    out = out.reshape(b, l, features)
    out = nn.Dense(x.shape[-1], dtype=self.dtype, name='out')(out)
    if self.tp_axis:
      out = _constrain(out, self.mesh, P(None, None, None))
    return out


def _flash_sharded_heads(q, k, v, *, causal: bool, mesh, tp_axis: str):
  """Flash attention with heads resident per tp shard via shard_map.

  The batch dim is also sharded over the mesh's data axis when the batch
  divides it — without that, a data x model mesh would all-gather q/k/v
  over 'data' and run the kernel on the full global batch per device.
  """
  from functools import partial

  from jax.experimental.shard_map import shard_map
  from jax.sharding import PartitionSpec as P

  from tensor2robot_tpu.parallel.mesh import DATA_AXIS

  data_size = int(mesh.shape.get(DATA_AXIS, 1))
  batch_axis = (DATA_AXIS
                if data_size > 1 and q.shape[0] % data_size == 0 else None)
  spec = P(batch_axis, None, tp_axis, None)
  fn = shard_map(
      partial(flash_lib.flash_attention, causal=causal),
      mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
      check_rep=False)
  return fn(q, k, v)


class TransformerBlock(nn.Module):
  """Pre-LN block: LN -> MHA -> +res, LN -> MLP(gelu) -> +res."""

  num_heads: int
  head_dim: int
  mlp_dim: int
  attention_mode: str = 'auto'
  causal: bool = True
  mesh: Optional[object] = None
  seq_axis: str = 'data'
  tp_axis: Optional[str] = None
  moe_experts: int = 0           # > 0: MoE MLP instead of the dense MLP
  moe_top_k: int = 2
  moe_capacity_factor: float = 1.25
  ep_axis: Optional[str] = None  # expert-parallel mesh axis for the MoE
  dropout_rate: float = 0.0
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, x: jnp.ndarray, train: bool = False):
    """Returns (x, aux_loss) — aux is the MoE load-balance term (0 when
    the block uses the dense MLP), threaded explicitly rather than via a
    mutable flax collection so it reaches the loss through the pure
    functional path the train step differentiates."""
    from jax.sharding import PartitionSpec as P

    # LayerNorm in f32: bf16 variance over long sequences loses precision.
    h = nn.LayerNorm(dtype=jnp.float32, name='ln_attn')(x).astype(self.dtype)
    h = MultiHeadAttention(
        num_heads=self.num_heads, head_dim=self.head_dim,
        attention_mode=self.attention_mode, causal=self.causal,
        mesh=self.mesh, seq_axis=self.seq_axis, tp_axis=self.tp_axis,
        dtype=self.dtype, name='attn')(h)
    if self.dropout_rate:
      h = nn.Dropout(self.dropout_rate, deterministic=not train)(h)
    x = x + h
    aux = jnp.zeros((), jnp.float32)
    h = nn.LayerNorm(dtype=jnp.float32, name='ln_mlp')(x).astype(self.dtype)
    if self.moe_experts:
      from tensor2robot_tpu.layers.moe import MoEMlp

      h, aux = MoEMlp(
          num_experts=self.moe_experts, expert_dim=self.mlp_dim,
          top_k=self.moe_top_k, capacity_factor=self.moe_capacity_factor,
          mesh=self.mesh, ep_axis=self.ep_axis,
          dtype=self.dtype, name='moe')(h)
    else:
      h = nn.Dense(self.mlp_dim, dtype=self.dtype, name='mlp_in')(h)
      if self.tp_axis:
        # Hidden activations shard over tp ([B, L, mlp/|model| each);
        # mlp_out's input-dim sharding then yields the closing psum.
        h = _constrain(h, self.mesh, P(None, None, self.tp_axis))
      h = nn.gelu(h)
      h = nn.Dense(x.shape[-1], dtype=self.dtype, name='mlp_out')(h)
      if self.tp_axis:
        h = _constrain(h, self.mesh, P(None, None, None))
    if self.dropout_rate:
      h = nn.Dropout(self.dropout_rate, deterministic=not train)(h)
    return x + h, aux


class TokenLearner(nn.Module):
  """Learns K attention maps that pool N spatial tokens to K tokens.

  RT-1's TokenLearner: per output token k, a weight map over the input
  tokens (softmax-normalized), applied as a weighted sum. Cuts the
  transformer's L from T*N to T*K (8x here) at negligible accuracy cost.
  """

  num_tokens: int
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, tokens: jnp.ndarray) -> jnp.ndarray:
    # tokens: [B, N, D] -> [B, K, D]
    x = nn.LayerNorm(dtype=jnp.float32, name='ln')(tokens).astype(self.dtype)
    maps = nn.Dense(self.num_tokens * 2, dtype=self.dtype, name='map_in')(x)
    maps = nn.gelu(maps)
    maps = nn.Dense(self.num_tokens, dtype=self.dtype, name='map_out')(maps)
    maps = jax.nn.softmax(maps.astype(jnp.float32), axis=1)  # over N
    return jnp.einsum('bnk,bnd->bkd', maps,
                      tokens.astype(jnp.float32)).astype(tokens.dtype)


class ImageTokenizer(nn.Module):
  """Conv stem turning a [B, H, W, 3] frame into [B, K, D] visual tokens.

  Four stride-2 convs (H/16 x W/16 spatial map), then TokenLearner down to
  ``num_tokens``. The reference's per-frame encoders (vision_layers
  BuildImagesToFeaturesModel) collapse each frame to ONE vector; tokens
  preserve spatial structure for the sequence model.
  """

  num_tokens: int = 8
  embed_dim: int = 512
  widths: tuple = (32, 64, 128, 256)
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, images: jnp.ndarray, train: bool = False) -> jnp.ndarray:
    x = images.astype(self.dtype)
    for i, width in enumerate(self.widths):
      x = nn.Conv(width, (3, 3), strides=(2, 2), dtype=self.dtype,
                  name='conv{}'.format(i))(x)
      x = nn.LayerNorm(dtype=jnp.float32,
                       name='ln{}'.format(i))(x).astype(self.dtype)
      x = nn.gelu(x)
    b = x.shape[0]
    x = x.reshape(b, -1, x.shape[-1])                    # [B, hw, C]
    x = nn.Dense(self.embed_dim, dtype=self.dtype, name='embed')(x)
    if self.num_tokens and self.num_tokens > x.shape[1]:
      raise ValueError(
          'num_tokens={} exceeds the conv stem\'s {} spatial tokens for '
          'this input size; lower num_tokens or raise the resolution.'
          .format(self.num_tokens, x.shape[1]))
    if self.num_tokens and self.num_tokens < x.shape[1]:
      x = TokenLearner(num_tokens=self.num_tokens, dtype=self.dtype,
                       name='token_learner')(x)
    # num_tokens == spatial tokens: pass-through (TokenLearner would be a
    # square resampling; small test configs rely on the identity).
    return x


class CausalTransformer(nn.Module):
  """Token sequence model: learned positions + N causal blocks + final LN.

  ``pipe_axis``: pipeline parallelism (parallel/pipeline.py). The blocks
  become ONE stacked param tree (``pipe_blocks``, leading dims
  ``[S, k]`` = [stage, block-within-stage], stage dim sharded over the
  pipe axis by PP_RULES_TRANSFORMER) and run as a GPipe pipeline with
  ``pipeline_microbatches`` microbatches; positions and the final LN stay
  outside the pipeline (replicated, cheap). Each stage runs
  ``num_layers / |pipe|`` consecutive blocks (virtual stages), so layer
  count only needs to be divisible by — not equal to — the stage count.
  Pipelined constraints (asserted at trace time): divisibility, no
  dropout, and no MoE/tp/ring inside the pipeline. NOTE: round 4's
  virtual-stage change moved pipe_blocks leaves from [L, ...] to
  [S, k, ...]; pipelined checkpoints saved before it need a one-off
  reshape (k == 1 splits the leading dim) — none are shipped in-tree.
  """

  num_layers: int
  num_heads: int
  head_dim: int
  mlp_dim: int
  max_length: int
  attention_mode: str = 'auto'
  mesh: Optional[object] = None
  seq_axis: str = 'data'
  tp_axis: Optional[str] = None
  moe_experts: int = 0
  moe_top_k: int = 2
  moe_capacity_factor: float = 1.25
  ep_axis: Optional[str] = None
  pipe_axis: Optional[str] = None
  pipeline_microbatches: int = 2
  pipeline_remat: bool = False
  dropout_rate: float = 0.0
  dtype: jnp.dtype = jnp.float32

  def _block(self, name: Optional[str] = None) -> 'TransformerBlock':
    return TransformerBlock(
        num_heads=self.num_heads, head_dim=self.head_dim,
        mlp_dim=self.mlp_dim, attention_mode=self.attention_mode,
        causal=True, mesh=self.mesh, seq_axis=self.seq_axis,
        tp_axis=self.tp_axis, moe_experts=self.moe_experts,
        moe_top_k=self.moe_top_k,
        moe_capacity_factor=self.moe_capacity_factor, ep_axis=self.ep_axis,
        dropout_rate=self.dropout_rate, dtype=self.dtype, name=name)

  @nn.compact
  def __call__(self, tokens: jnp.ndarray, train: bool = False):
    """Returns (encoded, aux_loss) — summed MoE load-balance loss over
    blocks, 0.0 for a dense (non-MoE) stack."""
    b, l, d = tokens.shape
    if l > self.max_length:
      raise ValueError('Sequence length {} exceeds max_length {}.'.format(
          l, self.max_length))
    pos = self.param('pos_embedding', nn.initializers.normal(0.02),
                     (self.max_length, d), jnp.float32)
    x = tokens + pos[None, :l].astype(tokens.dtype)
    aux_total = jnp.zeros((), jnp.float32)
    if self.pipe_axis:
      x = self._pipelined_blocks(x)
    else:
      for i in range(self.num_layers):
        x, aux = self._block(name='block{}'.format(i))(x, train=train)
        aux_total = aux_total + aux
    return nn.LayerNorm(dtype=jnp.float32, name='ln_final')(x), aux_total

  def _pipelined_blocks(self, x: jnp.ndarray) -> jnp.ndarray:
    from tensor2robot_tpu.parallel import pipeline as pipeline_lib

    if self.mesh is None:
      raise ValueError('pipe_axis requires a mesh.')
    stages = int(self.mesh.shape.get(self.pipe_axis, 0))
    if stages < 1 or self.num_layers % stages:
      raise ValueError(
          'pipelined transformer needs num_layers ({}) divisible by the '
          '{!r} axis size ({}); each stage runs num_layers/|pipe| blocks.'
          .format(self.num_layers, self.pipe_axis, stages))
    blocks_per_stage = self.num_layers // stages
    if self.dropout_rate or self.moe_experts:
      raise ValueError('pipelined blocks do not support dropout or MoE '
                       '(rngs/aux are not threaded through the pipeline).')
    if self.tp_axis or self.attention_mode == 'ring':
      # Both run their own sharding machinery (with_sharding_constraint /
      # a nested shard_map) inside pipeline_apply's shard_map body, where
      # every mesh axis is already manual — fail clearly instead of deep
      # inside JAX tracing.
      raise ValueError('pipelined blocks cannot combine with tp_axis or '
                       "attention_mode='ring' (nested sharding inside the "
                       'pipeline shard_map); plain xla/flash attention '
                       'works.')
    b, l, d = x.shape
    block = self._block()

    def init_stacked(rng):
      # Leading dims [S, k]: stage-major so leaf i on the stage axis holds
      # stage i's k consecutive blocks (layer order = stage*k + j).
      rngs = jax.random.split(rng, stages * blocks_per_stage)
      rngs = rngs.reshape((stages, blocks_per_stage) + rngs.shape[1:])
      return jax.vmap(jax.vmap(
          lambda r: block.init(r, jnp.zeros((1, l, d), x.dtype))['params']
      ))(rngs)

    stacked = self.param('pipe_blocks', init_stacked)

    def stage_fn(params, act):
      # params leaves: [k, ...] — apply the stage's k blocks in order.
      for j in range(blocks_per_stage):
        act, _ = block.apply(
            {'params': jax.tree.map(lambda p: p[j], params)}, act)
      return act

    mb = pipeline_lib.microbatch(x, self.pipeline_microbatches)
    out = pipeline_lib.pipeline_apply(stage_fn, stacked, mb, self.mesh,
                                      axis=self.pipe_axis,
                                      remat=self.pipeline_remat)
    return pipeline_lib.unmicrobatch(out)
