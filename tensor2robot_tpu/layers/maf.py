"""Masked autoregressive flow (MAF) for conditional action densities.

Parity target: /root/reference/research/vrgripper/maf.py:56-103 (maf_bijector
+ MAFDecoder), which builds on TFP's MaskedAutoregressiveFlow /
masked_autoregressive_default_template / Permute bijectors. Those are
re-implemented natively here:

  * :class:`MADE` — the masked autoregressive dense network (Germain et al.
    2015) producing per-dimension (shift, log_scale); masks are computed
    statically from degree assignments, so under jit they are constants
    folded into the kernels (one fused matmul per layer on the MXU).
  * :class:`MAFBijector` — a chain of MADE flows with fixed interleaved
    permutations (the reference's ``init_once`` non-trainable Permute
    variables become seed-derived constants). The density direction
    (``inverse_and_log_det``) is a single parallel pass — the hot path for
    training; sampling is the sequential direction (event_size passes,
    unrolled statically — action dims are small).

Conditioning follows the reference: the base distribution is N(mu, 1) with
mu a linear function of the conditioning features; the bijector itself is
unconditioned.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# TFP masked_autoregressive_default_template clips log_scale to this range.
LOG_SCALE_MIN_CLIP = -5.0
LOG_SCALE_MAX_CLIP = 3.0


def _hidden_degrees(width: int, event_size: int) -> np.ndarray:
  """MADE hidden-unit degrees cycling over 1..event_size-1 (or 1)."""
  max_degree = max(1, event_size - 1)
  return np.arange(width) % max_degree + 1


class MADE(nn.Module):
  """Masked dense network: y -> (shift, log_scale), autoregressive in y.

  Output dimension i depends only on inputs with degree < i+1, enforced by
  binary masks on the dense kernels (Germain et al. 2015, arXiv:1502.03509).
  """

  event_size: int
  hidden_layers: Tuple[int, ...] = (512, 512)

  @nn.compact
  def __call__(self, y: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    if any(width < self.event_size for width in self.hidden_layers):
      # ref maf.py:92-94 — narrower layers would sever autoregressive paths.
      raise ValueError(
          'MAF hidden layers have to be at least as wide as event size.')
    in_degrees = np.arange(1, self.event_size + 1)
    h = y
    prev_degrees = in_degrees
    for idx, width in enumerate(self.hidden_layers):
      degrees = _hidden_degrees(width, self.event_size)
      mask = (prev_degrees[:, None] <= degrees[None, :]).astype(np.float32)
      h = self._masked_dense(h, width, mask, 'masked_dense_{}'.format(idx))
      h = nn.relu(h)
      prev_degrees = degrees
    out_degrees = np.tile(np.arange(1, self.event_size + 1), 2)
    mask = (prev_degrees[:, None] < out_degrees[None, :]).astype(np.float32)
    out = self._masked_dense(h, 2 * self.event_size, mask, 'masked_dense_out')
    shift, log_scale = jnp.split(out, 2, axis=-1)
    log_scale = jnp.clip(log_scale, LOG_SCALE_MIN_CLIP, LOG_SCALE_MAX_CLIP)
    return shift, log_scale

  def _masked_dense(self, x, features: int, mask: np.ndarray, name: str):
    kernel = self.param(name + '_kernel', nn.initializers.xavier_uniform(),
                        (x.shape[-1], features), jnp.float32)
    bias = self.param(name + '_bias', nn.initializers.zeros, (features,),
                      jnp.float32)
    return x @ (kernel * jnp.asarray(mask)) + bias


class MAFBijector(nn.Module):
  """Chain of MADE flows with fixed permutations between them (ref :56-68).

  Matches the reference chain layout: flow_0, perm_0, flow_1, perm_1, ...
  with the final permutation dropped.
  """

  event_size: int
  num_flows: int = 1
  hidden_layers: Tuple[int, ...] = (512, 512)
  permutation_seed: int = 42

  def setup(self):
    self._flows = [
        MADE(event_size=self.event_size, hidden_layers=self.hidden_layers,
             name='made_{}'.format(i))
        for i in range(self.num_flows)
    ]
    rng = np.random.RandomState(self.permutation_seed)
    # One permutation after each flow except the last (ref drops it).
    self._permutations = [
        rng.permutation(self.event_size).astype(np.int32)
        for _ in range(self.num_flows - 1)
    ]

  def forward(self, u: jnp.ndarray) -> jnp.ndarray:
    """Sampling direction: base sample u -> data y. Sequential per flow."""
    y = u
    for i, flow in enumerate(self._flows):
      y = self._flow_forward(flow, y)
      if i < len(self._permutations):
        y = y[..., self._permutations[i]]
    return y

  def inverse_and_log_det(self, y: jnp.ndarray
                          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Density direction: data y -> base u, with sum log|det dT^-1/dy|."""
    u = y
    ildj = jnp.zeros(y.shape[:-1], jnp.float32)
    for i in reversed(range(self.num_flows)):
      if i < len(self._permutations):
        inverse_perm = np.argsort(self._permutations[i])
        u = u[..., inverse_perm]
      shift, log_scale = self._flows[i](u)
      u = (u - shift) * jnp.exp(-log_scale)
      ildj = ildj - jnp.sum(log_scale, axis=-1)
    return u, ildj

  def _flow_forward(self, flow: MADE, u: jnp.ndarray) -> jnp.ndarray:
    # y_i depends on y_{<i}: iterate event_size times; each pass fixes one
    # more dimension (standard autoregressive-sampling fixpoint).
    y = jnp.zeros_like(u)
    for _ in range(self.event_size):
      shift, log_scale = flow(y)
      y = u * jnp.exp(log_scale) + shift
    return y


class MAFDistribution(nn.Module):
  """MAF-transformed N(mu, 1) with conditioned means (ref MAFDecoder :72).

  ``__call__(params, ...)`` maps conditioning features to the base means via
  a linear layer, then:
    * returns a sample (``rng`` given) or the deterministic base-mean
      pushforward (``rng=None`` — robot-time serving);
    * if ``value`` is given, also returns its per-example log-prob.
  """

  output_size: int
  num_flows: int = 1
  hidden_layers: Tuple[int, ...] = (512, 512)
  permutation_seed: int = 42

  @nn.compact
  def __call__(self, params: jnp.ndarray,
               value: Optional[jnp.ndarray] = None,
               rng: Optional[jax.Array] = None):
    mus = nn.Dense(self.output_size, name='maf_mus')(
        jnp.asarray(params, jnp.float32))
    bijector = MAFBijector(
        event_size=self.output_size, num_flows=self.num_flows,
        hidden_layers=self.hidden_layers,
        permutation_seed=self.permutation_seed, name='bijector')
    u = mus if rng is None else (
        mus + jax.random.normal(rng, mus.shape, mus.dtype))
    sample = bijector.forward(u)
    if value is None:
      return sample, None
    base_u, ildj = bijector.inverse_and_log_det(
        jnp.asarray(value, jnp.float32))
    log_unnormalized = -0.5 * jnp.sum((base_u - mus) ** 2, axis=-1)
    log_normalization = 0.5 * self.output_size * np.log(2.0 * np.pi)
    log_prob = log_unnormalized - log_normalization + ildj
    return sample, log_prob
