"""ResNet v2 with per-block FiLM conditioning + endpoint taps.

Parity targets: /root/reference/layers/film_resnet_model.py (Model :396,
_apply_film :113 — the official-models ResNet fork with a
``film_generator_fn`` hook per block) and /root/reference/layers/resnet.py
(resnet_model :153, resnet_endpoints :86, linear_film_generator :104).

TPU-first notes: NHWC layout with channel counts that are multiples of
128 in the deep stages maps cleanly onto the MXU; batch norm runs in
float32 statistics while convs honor the module dtype (bf16 by default
under the framework's compute policy); endpoints are returned as a dict
instead of fished out of a graph by tensor name.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any

_BLOCK_SIZES = {
    18: [2, 2, 2, 2],
    34: [3, 4, 6, 3],
    50: [3, 4, 6, 3],
    101: [3, 4, 23, 3],
    152: [3, 8, 36, 3],
    200: [3, 24, 36, 3],
}


def get_block_sizes(resnet_size: int) -> Sequence[int]:
  try:
    return _BLOCK_SIZES[resnet_size]
  except KeyError:
    raise ValueError(
        'resnet_size {} not in {}'.format(resnet_size,
                                          sorted(_BLOCK_SIZES))) from None


def apply_film(activations: jnp.ndarray,
               gamma_beta: Optional[jnp.ndarray]) -> jnp.ndarray:
  """(1 + gamma) * h + beta, gamma_beta: [batch, 2*C] (ref _apply_film)."""
  if gamma_beta is None:
    return activations
  gamma, beta = jnp.split(gamma_beta, 2, axis=-1)
  gamma = (1.0 + gamma)[:, None, None, :].astype(activations.dtype)
  beta = beta[:, None, None, :].astype(activations.dtype)
  return gamma * activations + beta


class ResidualBlock(nn.Module):
  """v2 residual block: BN-ReLU-conv pre-activation ordering."""

  filters: int
  strides: int = 1
  projection: bool = False
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x, film_gamma_beta=None, train: bool = False):
    norm = partial(nn.BatchNorm, use_running_average=not train,
                   momentum=0.9, epsilon=1e-5, dtype=self.dtype)
    conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                   kernel_init=nn.initializers.variance_scaling(
                       2.0, 'fan_out', 'normal'))
    preact = nn.relu(norm(name='preact_bn')(x))
    shortcut = x
    if self.projection:
      shortcut = conv(self.filters, (1, 1), strides=(self.strides,) * 2,
                      name='proj_conv')(preact)
    y = conv(self.filters, (3, 3), strides=(self.strides,) * 2,
             padding='SAME', name='conv1')(preact)
    y = nn.relu(norm(name='bn1')(y))
    y = conv(self.filters, (3, 3), padding='SAME', name='conv2')(y)
    y = apply_film(y, film_gamma_beta)
    return shortcut + y


class BottleneckBlock(nn.Module):
  """v2 bottleneck block (1x1 -> 3x3 -> 1x1, 4x expansion)."""

  filters: int
  strides: int = 1
  projection: bool = False
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x, film_gamma_beta=None, train: bool = False):
    norm = partial(nn.BatchNorm, use_running_average=not train,
                   momentum=0.9, epsilon=1e-5, dtype=self.dtype)
    conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                   kernel_init=nn.initializers.variance_scaling(
                       2.0, 'fan_out', 'normal'))
    preact = nn.relu(norm(name='preact_bn')(x))
    shortcut = x
    if self.projection:
      shortcut = conv(4 * self.filters, (1, 1), strides=(self.strides,) * 2,
                      name='proj_conv')(preact)
    y = conv(self.filters, (1, 1), name='conv1')(preact)
    y = nn.relu(norm(name='bn1')(y))
    y = conv(self.filters, (3, 3), strides=(self.strides,) * 2,
             padding='SAME', name='conv2')(y)
    y = nn.relu(norm(name='bn2')(y))
    y = conv(4 * self.filters, (1, 1), name='conv3')(y)
    y = apply_film(y, film_gamma_beta)
    return shortcut + y


class ResNet(nn.Module):
  """FiLM-conditionable ResNet v2 (ref film_resnet_model.Model :396).

  ``film_gamma_betas``: list (per block layer) of lists (per block) of
  [batch, 2*C] tensors or None — the exact contract of the reference's
  ``film_generator_fn`` output (linear_film_generator :104).
  """

  resnet_size: int = 50
  num_classes: int = 1001
  num_filters: int = 64
  dtype: Any = jnp.float32

  @property
  def block_sizes(self) -> Sequence[int]:
    return get_block_sizes(self.resnet_size)

  @property
  def bottleneck(self) -> bool:
    return self.resnet_size >= 50

  @property
  def filter_sizes(self) -> Sequence[int]:
    # Channel size of the FiLM-modulated activation per block layer.
    mult = 4 if self.bottleneck else 1
    return [self.num_filters * (2 ** i) * mult for i in range(4)]

  @nn.compact
  def __call__(self, images: jnp.ndarray,
               film_gamma_betas: Optional[Sequence[Sequence[Any]]] = None,
               train: bool = False,
               include_head: bool = True
               ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    endpoints: Dict[str, jnp.ndarray] = {}
    block_cls = BottleneckBlock if self.bottleneck else ResidualBlock
    block_strides = [1, 2, 2, 2]
    x = images.astype(self.dtype)
    x = nn.Conv(self.num_filters, (7, 7), strides=(2, 2), padding='SAME',
                use_bias=False, dtype=self.dtype,
                kernel_init=nn.initializers.variance_scaling(
                    2.0, 'fan_out', 'normal'),
                name='initial_conv')(x)
    endpoints['initial_conv'] = x
    x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
    endpoints['initial_max_pool'] = x
    for i, (num_blocks, stride) in enumerate(
        zip(self.block_sizes, block_strides)):
      layer_films = (film_gamma_betas[i] if film_gamma_betas is not None
                     else [None] * num_blocks)
      if len(layer_films) != num_blocks:
        raise ValueError(
            'block layer {} expects {} FiLM vectors, got {}.'.format(
                i + 1, num_blocks, len(layer_films)))
      filters = self.num_filters * (2 ** i)
      for j in range(num_blocks):
        x = block_cls(
            filters=filters,
            strides=stride if j == 0 else 1,
            projection=(j == 0),
            dtype=self.dtype,
            name='block_layer{}_{}'.format(i + 1, j))(
                x, film_gamma_beta=layer_films[j], train=train)
      endpoints['block_layer{}'.format(i + 1)] = x
    x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-5, dtype=self.dtype,
                             name='final_bn')(x))
    endpoints['pre_final_pool'] = x
    x = jnp.mean(x, axis=(1, 2))
    endpoints['final_reduce_mean'] = x
    if include_head:
      x = nn.Dense(self.num_classes, dtype=jnp.float32,
                   name='final_dense')(x)
      endpoints['final_dense'] = x
    return x, endpoints


class LinearFilmGenerator(nn.Module):
  """Per-block-layer linear FiLM head (ref linear_film_generator :104)."""

  block_sizes: Sequence[int]
  filter_sizes: Sequence[int]
  enabled_block_layers: Optional[Sequence[bool]] = None

  @nn.compact
  def __call__(self, embedding: jnp.ndarray):
    enabled = self.enabled_block_layers
    if enabled is not None and len(enabled) != len(self.block_sizes):
      raise ValueError(
          'Got {} bools for enabled_block_layers, expected {}.'.format(
              len(enabled), len(self.block_sizes)))
    film_gamma_betas = []
    for i, num_blocks in enumerate(self.block_sizes):
      if enabled is not None and not enabled[i]:
        film_gamma_betas.append([None] * num_blocks)
        continue
      out_size = num_blocks * self.filter_sizes[i] * 2
      flat = nn.Dense(out_size, name='film{}'.format(i))(embedding)
      film_gamma_betas.append(list(jnp.split(flat, num_blocks, axis=-1)))
    return film_gamma_betas


def resnet_model(images: jnp.ndarray,
                 variables,
                 train: bool = False,
                 num_classes: int = 1001,
                 resnet_size: int = 50,
                 film_embedding: Optional[jnp.ndarray] = None,
                 film_generator: Optional[Callable] = None,
                 dtype: Any = jnp.float32):
  """Functional convenience wrapper mirroring resnet_model (ref :153)."""
  model = ResNet(resnet_size=resnet_size, num_classes=num_classes,
                 dtype=dtype)
  film_gamma_betas = None
  if film_embedding is not None and film_generator is not None:
    film_gamma_betas = film_generator(film_embedding)
  if train:
    (outputs, endpoints), new_state = model.apply(
        variables, images, film_gamma_betas=film_gamma_betas, train=True,
        mutable=['batch_stats'])
    return outputs, endpoints, new_state
  return model.apply(variables, images, film_gamma_betas=film_gamma_betas,
                     train=False)
