"""Mixture density networks, pure-functional.

Parity target: /root/reference/layers/mdn.py (get_mixture_distribution :34,
predict_mdn_params :77, gaussian_mixture_approximate_mode :118,
MDNDecoder :129). The tfp MixtureSameFamily distribution object becomes a
small frozen parameter dataclass + pure log-prob/mode/sample functions —
the decoder stays stateless so MAML-style wrappers can call it repeatedly
(the reference's TODO about stateful decoders disappears by construction).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class MixtureParams(NamedTuple):
  """Diagonal Gaussian mixture parameters.

  alphas: [..., K] mixture logits.
  mus: [..., K, D] component means.
  sigmas: [..., K, D] component scales (positive).
  """
  alphas: jnp.ndarray
  mus: jnp.ndarray
  sigmas: jnp.ndarray


def get_mixture_distribution(params: jnp.ndarray,
                             num_alphas: int,
                             sample_size: int,
                             output_mean: Optional[jnp.ndarray] = None
                             ) -> MixtureParams:
  """Splits a flat param tensor into mixture parameters (ref mdn.py:34).

  Args:
    params: [..., num_alphas + 2*num_alphas*sample_size].
    num_alphas: number of mixture components K.
    sample_size: event size D.
    output_mean: optional translation added to every component mean.
  """
  num_mus = num_alphas * sample_size
  if params.shape[-1] != num_alphas + 2 * num_mus:
    raise ValueError(
        'Params has unexpected trailing dim {} (want {}).'.format(
            params.shape[-1], num_alphas + 2 * num_mus))
  alphas = params[..., :num_alphas]
  batch_shape = params.shape[:-1]
  mus = params[..., num_alphas:num_alphas + num_mus].reshape(
      batch_shape + (num_alphas, sample_size))
  raw_sigmas = params[..., num_alphas + num_mus:].reshape(
      batch_shape + (num_alphas, sample_size))
  if output_mean is not None:
    mus = mus + output_mean
  return MixtureParams(alphas=alphas, mus=mus,
                       sigmas=jax.nn.softplus(raw_sigmas))


def mixture_log_prob(gm: MixtureParams, value: jnp.ndarray) -> jnp.ndarray:
  """log p(value) under the mixture; value: [..., D] -> [...]."""
  log_alphas = jax.nn.log_softmax(gm.alphas, axis=-1)          # [..., K]
  diff = (value[..., None, :] - gm.mus) / gm.sigmas            # [..., K, D]
  log_det = jnp.sum(jnp.log(gm.sigmas), axis=-1)               # [..., K]
  d = gm.mus.shape[-1]
  component_lp = (-0.5 * jnp.sum(diff * diff, axis=-1)
                  - log_det - 0.5 * d * np.log(2.0 * np.pi))
  return jax.nn.logsumexp(log_alphas + component_lp, axis=-1)


def gaussian_mixture_approximate_mode(gm: MixtureParams) -> jnp.ndarray:
  """Mean of the most probable component (ref mdn.py:118)."""
  mode_alpha = jnp.argmax(gm.alphas, axis=-1)                  # [...]
  return jnp.take_along_axis(
      gm.mus, mode_alpha[..., None, None], axis=-2).squeeze(-2)


def mixture_sample(gm: MixtureParams, rng: jax.Array) -> jnp.ndarray:
  """Draws one sample: component via categorical, then diagonal normal."""
  k_rng, n_rng = jax.random.split(rng)
  component = jax.random.categorical(k_rng, gm.alphas, axis=-1)
  mu = jnp.take_along_axis(
      gm.mus, component[..., None, None], axis=-2).squeeze(-2)
  sigma = jnp.take_along_axis(
      gm.sigmas, component[..., None, None], axis=-2).squeeze(-2)
  return mu + sigma * jax.random.normal(n_rng, mu.shape, mu.dtype)


class MDNParamsLayer(nn.Module):
  """Linear head producing mixture params (ref predict_mdn_params :77).

  With ``condition_sigmas=False`` the scales are free learned variables
  initialized so softplus(sigma_raw) == 1, broadcast over the batch.
  """

  num_alphas: int
  sample_size: int
  condition_sigmas: bool = False

  @nn.compact
  def __call__(self, inputs: jnp.ndarray) -> jnp.ndarray:
    num_mus = self.num_alphas * self.sample_size
    num_outputs = self.num_alphas + num_mus
    if self.condition_sigmas:
      num_outputs += num_mus
    dist_params = nn.Dense(num_outputs, name='mdn_params')(inputs)
    if not self.condition_sigmas:
      sigmas = self.param(
          'mdn_stddev_inputs',
          nn.initializers.constant(np.log(np.e - 1.0)), (num_mus,),
          jnp.float32)
      tiled = jnp.broadcast_to(
          sigmas.astype(dist_params.dtype),
          dist_params.shape[:-1] + (num_mus,))
      dist_params = jnp.concatenate([dist_params, tiled], axis=-1)
    return dist_params


class MDNDecoder(nn.Module):
  """Action decoder head (ref MDNDecoder :129), stateless.

  __call__ returns (action, mixture_params); the loss is the separate pure
  function :func:`mdn_loss` over (mixture_params, labels).
  """

  num_mixture_components: int = 1
  output_size: int = 1
  condition_sigmas: bool = False

  @nn.compact
  def __call__(self, params_input: jnp.ndarray):
    dist_params = MDNParamsLayer(
        num_alphas=self.num_mixture_components,
        sample_size=self.output_size,
        condition_sigmas=self.condition_sigmas)(params_input)
    gm = get_mixture_distribution(
        dist_params.astype(jnp.float32), self.num_mixture_components,
        self.output_size)
    action = gaussian_mixture_approximate_mode(gm)
    return action, gm


def mdn_loss(gm: MixtureParams, target: jnp.ndarray) -> jnp.ndarray:
  """Mean negative log-likelihood across batch/sequence dims."""
  return -jnp.mean(mixture_log_prob(gm, target))
