"""SNAIL: attentive temporal meta-learner building blocks.

Parity target: /root/reference/layers/snail.py (CausalConv :35, DenseBlock
:60, TCBlock :78, CausallyMaskedSoftmax :95, AttentionBlock :119 — the
architecture of arXiv:1707.03141). Causal padding + static unrolled
dilation stack keeps everything shape-static for XLA; the attention mask
is additive -inf on the strict upper triangle.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


class CausalConv(nn.Module):
  """Causal dilated 1D convolution over [batch, time, channels]."""

  filters: int
  dilation_rate: int = 1
  kernel_size: int = 2

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    causal_pad = (self.kernel_size - 1) * self.dilation_rate
    x = jnp.pad(x, ((0, 0), (causal_pad, 0), (0, 0)))
    return nn.Conv(
        features=self.filters,
        kernel_size=(self.kernel_size,),
        kernel_dilation=(self.dilation_rate,),
        padding='VALID')(x)


class DenseBlock(nn.Module):
  """Gated causal conv whose activations concatenate onto the input."""

  filters: int
  dilation_rate: int = 1

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    xf = CausalConv(self.filters, self.dilation_rate, name='xf')(x)
    xg = CausalConv(self.filters, self.dilation_rate, name='xg')(x)
    activations = jnp.tanh(xf) * nn.sigmoid(xg)
    return jnp.concatenate([x, activations], axis=2)


class TCBlock(nn.Module):
  """Stack of DenseBlocks with exponentially increasing dilation.

  Output channels = channels + filters * ceil(log2(sequence_length)).
  """

  sequence_length: int
  filters: int

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    for i in range(1, int(np.ceil(np.log2(self.sequence_length))) + 1):
      x = DenseBlock(self.filters, 2 ** i, name='DenseBlock_%d' % i)(x)
    return x


def causally_masked_softmax(logits: jnp.ndarray) -> jnp.ndarray:
  """Row-wise softmax over [..., T, T] with j > i masked out."""
  t = logits.shape[-1]
  mask = jnp.tril(jnp.ones((t, t), bool))
  masked = jnp.where(mask, logits, -jnp.inf)
  probs = nn.softmax(masked, axis=-1)
  # Exact zeros above the diagonal (softmax of -inf already is, but keep
  # the reference's explicit band_part semantics for bit-stability).
  return jnp.where(mask, probs, 0.0)


class AttentionBlock(nn.Module):
  """Causal single-head KV attention; read concatenates onto the input."""

  key_size: int
  value_size: int

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, dict]:
    key = nn.Dense(self.key_size)(x)
    query = nn.Dense(self.key_size)(x)
    logits = jnp.einsum('btk,bsk->bts', query, key)
    probs = causally_masked_softmax(
        logits / np.sqrt(self.key_size))
    values = nn.Dense(self.value_size)(x)
    read = jnp.einsum('bts,bsv->btv', probs, values)
    result = jnp.concatenate([x, read], axis=2)
    return result, {'attn_prob': probs}
