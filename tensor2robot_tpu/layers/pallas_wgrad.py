"""Pallas 5x5 conv weight-gradient kernel — the measured record.

THE EXPERIMENT (round 4, closing VERDICT r3 item 2): QT-Opt's headline is
bounded by its six 5x5/64-channel conv weight-gradients
(/root/reference/research/qtopt/networks.py:449-520 defines the stack; the
per-fusion profile in docs/performance.md attributes 42.3 ms of the 175 ms
batch-512 step to them, running at ~96 TF/s inside XLA's fused step). The
open question from round 3 was whether a hand Mosaic kernel in im2col/
matmul form could beat XLA's conv emitter. It cannot — measured on one
v5e, isolated op, x/dy [512, 79, 79, 64] bf16, dW [5, 5, 64, 64] f32
(654 GFLOP), same chained-timing harness for every row:

  XLA wgrad (jax.vjp of conv_general_dilated)   10.3 ms   63.8 TF/s
  v1 (this file): 25 shifted-slice dots/chunk   23.7 ms   27.6 TF/s
  v2: in-kernel 128-packed operands             30.4 ms   21.5 TF/s
  v3: HBM-prebuilt 128-packed, zero in-kernel
      sublane slicing, pure 128x128 passes      31.7 ms   20.7 TF/s

v2/v3 tested the "quarter-MXU" theory — that 64x64 output tiles waste the
128x128 systolic array and packing 4 kernel offsets per pass via the
shifted-operand identity (sum_p X[p+a]dY[p+b] = dW[a-b] under zero
padding) would ~4x the pass rate. The packed passes were NOT faster:
Mosaic's lowering of row-contracted dots ([R,64]^T @ [R,64], contraction
on the sublane axis) pays an operand relayout that dominates regardless
of output width, and the extra operand bytes (doubled channels) make v2/v3
strictly worse. With the strongest formulation 2.3x behind XLA's isolated
emitter — which itself runs 50% faster again inside the fused step — the
conv/wgrad emitter wall stands. The "why 4,000 ex/s is out of reach"
case in docs/performance.md now rests on measurement, not extrapolation.

Mosaic/v5e restrictions hit on the way (each cost a compile cycle):
  * odd sublane extents (W=79 -> 83-wide blocks) crash the bf16 packer
    outright — pad spatial dims to multiples of 8;
  * dynamic-start slices on the sublane axis need provably 8-aligned
    offsets ("cannot statically prove index is a multiple of 8");
  * lane-dim concat of two slices with different sublane offsets fails
    ("result/input offset mismatch on non-concat dimension") — reshape
    each slice to 2D first to normalize layouts;
  * a python-unrolled 25-slice loop keeps every shifted copy live and
    blows the 16 MB scoped-VMEM cap at real shapes — chunk the H axis
    through a fori_loop and keep temporaries ~100 KB.

The kernel is kept (a) as the parity-tested record backing the numbers
above, (b) because its structure (outer-dim windowing + chunked
accumulation) is the template any future conv-kernel attempt would start
from. Use XLA's conv for production training; nothing imports this on the
hot path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

KH = KW = 5
_PAD = 2  # SAME padding for 5x5
_CHUNK = 8  # H rows per accumulation chunk


def _wgrad_kernel(x_ref, dy_ref, out_ref):
  """Accumulates dW[25*C, C] f32 over batch-tile grid steps.

  x_ref: [bt, Hp+4, Wp+4, C] bf16, zero-padded (SAME + alignment).
  dy_ref: [bt, Hp, Wp, C] bf16, zero-padded (alignment pads kill the
    extra products exactly).
  """
  i = pl.program_id(0)
  bt, _, _, c = x_ref.shape
  _, h, w, _ = dy_ref.shape
  cs = _CHUNK

  @pl.when(i == 0)
  def _():
    out_ref[...] = jnp.zeros_like(out_ref)

  def body(ch, carry):
    dy = dy_ref[:, pl.dslice(ch * cs, cs), :, :].reshape(bt * cs * w, c)
    for dh in range(KH):
      xrow = x_ref[:, pl.dslice(ch * cs + dh, cs), :, :]
      for dw in range(KW):
        xs = xrow[:, :, dw:dw + w, :].reshape(bt * cs * w, c)
        acc = jax.lax.dot_general(
            xs, dy, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        out_ref[dh * (KW * c) + dw * c:dh * (KW * c) + (dw + 1) * c,
                :] += acc
    return carry

  jax.lax.fori_loop(0, h // cs, body, 0)


@functools.partial(jax.jit, static_argnames=('batch_tile', 'interpret'))
def conv5x5_wgrad(x: jnp.ndarray, dy: jnp.ndarray, batch_tile: int = 2,
                  interpret: bool = False) -> jnp.ndarray:
  """dW of a 5x5 stride-1 SAME conv: x [B,H,W,C], dy [B,H,W,C] -> [5,5,C,C].

  Matches jax.vjp of lax.conv_general_dilated('NHWC','HWIO','NHWC') with
  f32 accumulation (parity test: tests/test_layers.py).
  """
  b, h, w, c = x.shape
  if b % batch_tile:
    raise ValueError('batch %d not divisible by batch_tile %d'
                     % (b, batch_tile))
  hp = -(-h // _CHUNK) * _CHUNK
  wp = -(-w // 8) * 8
  xp = jnp.pad(x, ((0, 0), (_PAD, _PAD + hp - h), (_PAD, _PAD + wp - w),
                   (0, 0)))
  dyp = jnp.pad(dy, ((0, 0), (0, hp - h), (0, wp - w), (0, 0)))
  out = pl.pallas_call(
      _wgrad_kernel,
      grid=(b // batch_tile,),
      in_specs=[
          pl.BlockSpec((batch_tile, hp + 4, wp + 4, c),
                       lambda i: (i, 0, 0, 0)),
          pl.BlockSpec((batch_tile, hp, wp, c), lambda i: (i, 0, 0, 0)),
      ],
      out_specs=pl.BlockSpec((KH * KW * c, c), lambda i: (0, 0)),
      out_shape=jax.ShapeDtypeStruct((KH * KW * c, c), jnp.float32),
      interpret=interpret,
  )(xp, dyp)
  return out.reshape(KH, KW, c, c)
