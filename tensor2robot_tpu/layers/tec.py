"""Task-embedded control (TEC) embedding layers + contrastive loss.

Parity target: /root/reference/layers/tec.py (embed_fullstate :30,
embed_condition_images :54, reduce_temporal_embeddings :91,
compute_embedding_contrastive_loss :136).
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from tensor2robot_tpu.layers.vision_layers import ImagesToFeaturesNet


class EmbedFullstate(nn.Module):
  """MLP embedding of non-image state vectors [N, F] -> [N, embed]."""

  embed_size: int
  fc_layers: Sequence[int] = (100,)

  @nn.compact
  def __call__(self, fullstate: jnp.ndarray) -> jnp.ndarray:
    x = fullstate
    for width in self.fc_layers:
      x = nn.Dense(width)(x)
      x = nn.LayerNorm()(x)
      x = nn.relu(x)
    return nn.Dense(self.embed_size)(x)


class EmbedConditionImages(nn.Module):
  """Image embedding via the keypoint tower + optional MLP head."""

  fc_layers: Optional[Sequence[int]] = None

  @nn.compact
  def __call__(self, condition_image: jnp.ndarray,
               train: bool = False) -> jnp.ndarray:
    if condition_image.ndim != 4:
      raise ValueError(
          'Image has unexpected shape {}.'.format(condition_image.shape))
    embedding, _ = ImagesToFeaturesNet()(condition_image, train=train)
    if self.fc_layers is not None:
      for width in self.fc_layers[:-1]:
        embedding = nn.Dense(width)(embedding)
        embedding = nn.LayerNorm()(embedding)
        embedding = nn.relu(embedding)
      embedding = nn.Dense(self.fc_layers[-1])(embedding)
    return embedding


class ReduceTemporalEmbeddings(nn.Module):
  """[N, T, F] episode embedding -> [N, output_size] via 1D convs + MLP."""

  output_size: int
  conv1d_layers: Optional[Sequence[int]] = (64,)
  fc_hidden_layers: Sequence[int] = (100,)
  kernel_size: int = 10

  @nn.compact
  def __call__(self, temporal_embedding: jnp.ndarray) -> jnp.ndarray:
    if temporal_embedding.ndim != 3:
      raise ValueError('Temporal embedding has unexpected shape {}.'.format(
          temporal_embedding.shape))
    x = temporal_embedding
    if self.conv1d_layers is not None:
      for num_filters in self.conv1d_layers:
        x = nn.Conv(num_filters, (self.kernel_size,), padding='VALID',
                    use_bias=False)(x)
        x = nn.relu(x)
        x = nn.LayerNorm()(x)
    x = x.reshape((x.shape[0], -1))
    for width in self.fc_hidden_layers:
      x = nn.Dense(width)(x)
      x = nn.LayerNorm()(x)
      x = nn.relu(x)
    return nn.Dense(self.output_size)(x)


def contrastive_loss(labels: jnp.ndarray,
                     embeddings_anchor: jnp.ndarray,
                     embeddings_positive: jnp.ndarray,
                     margin: float = 1.0) -> jnp.ndarray:
  """Classic Hadsell et al. contrastive loss on embedding pairs.

  labels: [N] bool/int, 1 when the pair is a genuine match. Matches the
  tf_slim metric_learning.contrastive_loss semantics the reference calls.
  """
  distances = jnp.sqrt(
      jnp.sum((embeddings_anchor - embeddings_positive) ** 2, axis=-1)
      + 1e-12)
  labels_f = labels.astype(distances.dtype)
  match_loss = labels_f * distances ** 2
  mismatch_loss = (1.0 - labels_f) * jnp.maximum(margin - distances, 0.0) ** 2
  return jnp.mean(match_loss + mismatch_loss)


def compute_embedding_contrastive_loss(
    inf_embedding: jnp.ndarray,
    con_embedding: jnp.ndarray,
    positives: Optional[jnp.ndarray] = None) -> jnp.ndarray:
  """Anchor = task-0 inference embedding vs every task's condition embedding.

  inf_embedding: [num_tasks, num_inf_episodes, K] (L2-normalized).
  con_embedding: [num_tasks, num_con_episodes, K].
  positives: optional [num_tasks] bool; default: only task 0 is positive.
  """
  if inf_embedding.ndim != 3:
    raise ValueError(
        'Unexpected inf_embedding shape: {}.'.format(inf_embedding.shape))
  if con_embedding.ndim != 3:
    raise ValueError(
        'Unexpected con_embedding shape: {}.'.format(con_embedding.shape))
  avg_inf = jnp.mean(inf_embedding, axis=1)
  avg_con = jnp.mean(con_embedding, axis=1)
  anchor = avg_inf[0:1]
  if positives is not None:
    labels = positives
  else:
    labels = jnp.arange(avg_con.shape[0]) == 0
  anchor_tiled = jnp.broadcast_to(anchor, avg_con.shape)
  return contrastive_loss(labels, anchor_tiled, avg_con)
