"""ExportedSavedModelPredictor: poll + serve jax2tf SavedModel exports.

Parity target: /root/reference/predictors/exported_savedmodel_predictor.py
:50-274 — the predictor that consumes the SavedModel directory a
TF-Serving-style robot stack watches. The repo's native polling predictor
(exported_model_predictor.py) consumes its own StableHLO artifact; this
one closes the loop on the OTHER export format the framework writes
(export/tf_savedmodel.py): numeric-timestamp version polling with
tmp-dir/partial skipping (:238-274), assets.extra/t2r_assets.pbtxt spec
loading (:162-170), global-step reconciliation (:181-189), and vanished-
version retry (:160-198) are inherited from the shared polling machinery;
serving goes through the SavedModel's own signatures:

  * ``predict``            -> signature 'serving_default' (per-feature
                              tensors, batch-polymorphic)
  * ``predict_serialized`` -> signature 'tf_example' (serialized
                              tf.Example bytes, parsed IN-graph — the
                              reference's tf_example receiver)

TensorFlow imports lazily: only SavedModel-consuming robot hosts pay it.
"""

from __future__ import annotations

import os
from typing import Dict

import numpy as np

from tensor2robot_tpu.predictors.exported_model_predictor import (
    ExportedModelPredictor,
)
from tensor2robot_tpu.specs import assets as assets_lib


class ExportedSavedModelPredictor(ExportedModelPredictor):
  """Serves the newest SavedModel version under an export root."""

  def __init__(self, export_dir: str, timeout: float = 600.0):
    super().__init__(export_dir, t2r_model=None, timeout=timeout)
    self._loaded_module = None       # keeps signature resources alive
    self._signature = None
    self._tf_example_signature = None

  # -- restore ---------------------------------------------------------------

  def _try_load_version(self, version: int) -> bool:
    import tensorflow as tf  # lazy: serving hosts only

    version_dir = os.path.join(self._export_dir, str(version))
    try:
      if not os.path.exists(os.path.join(version_dir, 'saved_model.pb')):
        return False  # partial write or a non-SavedModel artifact dir
      loaded = tf.saved_model.load(version_dir)
      feature_spec, label_spec, step = assets_lib.load_t2r_assets_from_file(
          os.path.join(version_dir, assets_lib.EXTRA_ASSETS_DIRECTORY,
                       assets_lib.T2R_ASSETS_FILENAME))
    except (OSError, ValueError, tf.errors.OpError):
      return False  # racing GC/partial write: caller falls back
    if 'serving_default' not in loaded.signatures:
      return False
    self._loaded_module = loaded
    self._signature = loaded.signatures['serving_default']
    self._tf_example_signature = loaded.signatures.get('tf_example')
    self._feature_spec = feature_spec
    self._label_spec = label_spec
    self._version = version
    if step is None:
      try:
        step = assets_lib.load_global_step_from_file(version_dir)
      except (OSError, ValueError):
        step = 0
    self._global_step = int(step or 0)
    self._model_path = version_dir
    return True

  # -- serving ---------------------------------------------------------------

  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    import tensorflow as tf

    self.assert_is_loaded()
    outputs = self._signature(
        **{key: tf.constant(np.asarray(value))
           for key, value in features.items()})
    return {key: np.asarray(value) for key, value in outputs.items()}

  def predict_serialized(self, records) -> Dict[str, np.ndarray]:
    """tf.Example receiver via the SavedModel's IN-graph parser."""
    import tensorflow as tf

    self.assert_is_loaded()
    if self._tf_example_signature is None:
      raise ValueError(
          'SavedModel at {} exports no tf_example signature.'.format(
              self._model_path))
    if isinstance(records, bytes):
      records = [records]
    outputs = self._tf_example_signature(tf.constant(list(records)))
    return {key: np.asarray(value) for key, value in outputs.items()}

  @property
  def variables(self):
    raise AttributeError(
        'ExportedSavedModelPredictor serves through SavedModel signatures; '
        'it holds no raw variables pytree (use ExportedModelPredictor for '
        'variable-level access).')

  @property
  def is_loaded(self) -> bool:
    return self._signature is not None

  def close(self) -> None:
    self._loaded_module = None
    self._signature = None
    self._tf_example_signature = None
    self._version = None  # see ExportedModelPredictor.close
