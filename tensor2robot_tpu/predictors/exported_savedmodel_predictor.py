"""ExportedSavedModelPredictor: poll + serve jax2tf SavedModel exports.

Parity target: /root/reference/predictors/exported_savedmodel_predictor.py
:50-274 — the predictor that consumes the SavedModel directory a
TF-Serving-style robot stack watches. The repo's native polling predictor
(exported_model_predictor.py) consumes its own StableHLO artifact; this
one closes the loop on the OTHER export format the framework writes
(export/tf_savedmodel.py): numeric-timestamp version polling with
tmp-dir/partial skipping (:238-274), assets.extra/t2r_assets.pbtxt spec
loading (:162-170), global-step reconciliation (:181-189), and vanished-
version retry (:160-198) are inherited from the shared polling machinery;
serving goes through the SavedModel's own signatures:

  * ``predict``            -> signature 'serving_default' (per-feature
                              tensors, batch-polymorphic)
  * ``predict_serialized`` -> signature 'tf_example' (serialized
                              tf.Example bytes, parsed IN-graph — the
                              reference's tf_example receiver)

TensorFlow imports lazily: only SavedModel-consuming robot hosts pay it.
"""

from __future__ import annotations

import os
from typing import Dict

import numpy as np

from tensor2robot_tpu.predictors.exported_model_predictor import (
    ExportedModelPredictor,
)
from tensor2robot_tpu.specs import assets as assets_lib


class _LoadedSavedModel:
  """One loaded SavedModel version, swapped in as a single reference.

  Same versioned-snapshot contract as the parent's ``_Loaded`` (ISSUE
  8): the module (which keeps the signatures' resources alive), both
  signatures, specs, and version metadata ride ONE atomically-assigned
  object, so a predict racing a hot-swap can never pair one version's
  signature with another's spec or step. Attribute names match what the
  parent's metadata properties read.
  """

  __slots__ = ('module', 'signature', 'tf_example_signature',
               'feature_spec', 'label_spec', 'version', 'global_step',
               'model_path')

  def __init__(self, module, signature, tf_example_signature, feature_spec,
               label_spec, version, global_step, model_path):
    self.module = module
    self.signature = signature
    self.tf_example_signature = tf_example_signature
    self.feature_spec = feature_spec
    self.label_spec = label_spec
    self.version = version
    self.global_step = global_step
    self.model_path = model_path


class ExportedSavedModelPredictor(ExportedModelPredictor):
  """Serves the newest SavedModel version under an export root."""

  def __init__(self, export_dir: str, timeout: float = 600.0):
    super().__init__(export_dir, t2r_model=None, timeout=timeout)

  # -- restore ---------------------------------------------------------------

  def _try_load_version(self, version: int) -> bool:
    import tensorflow as tf  # lazy: serving hosts only

    version_dir = os.path.join(self._export_dir, str(version))
    try:
      if not os.path.exists(os.path.join(version_dir, 'saved_model.pb')):
        return False  # partial write or a non-SavedModel artifact dir
      loaded = tf.saved_model.load(version_dir)
      feature_spec, label_spec, step = assets_lib.load_t2r_assets_from_file(
          os.path.join(version_dir, assets_lib.EXTRA_ASSETS_DIRECTORY,
                       assets_lib.T2R_ASSETS_FILENAME))
    except (OSError, ValueError, tf.errors.OpError):
      return False  # racing GC/partial write: caller falls back
    if 'serving_default' not in loaded.signatures:
      return False
    if step is None:
      try:
        step = assets_lib.load_global_step_from_file(version_dir)
      except (OSError, ValueError):
        step = 0
    self._loaded = _LoadedSavedModel(
        module=loaded, signature=loaded.signatures['serving_default'],
        tf_example_signature=loaded.signatures.get('tf_example'),
        feature_spec=feature_spec, label_spec=label_spec, version=version,
        global_step=int(step or 0), model_path=version_dir)
    return True

  # -- serving ---------------------------------------------------------------

  def predict_versioned(self, features: Dict[str, np.ndarray]):
    import tensorflow as tf

    loaded = self._loaded_snapshot()
    outputs = loaded.signature(
        **{key: tf.constant(np.asarray(value))
           for key, value in features.items()})
    return ({key: np.asarray(value) for key, value in outputs.items()},
            loaded.version)

  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    return self.predict_versioned(features)[0]

  def predict_serialized(self, records) -> Dict[str, np.ndarray]:
    """tf.Example receiver via the SavedModel's IN-graph parser."""
    import tensorflow as tf

    loaded = self._loaded_snapshot()
    if loaded.tf_example_signature is None:
      raise ValueError(
          'SavedModel at {} exports no tf_example signature.'.format(
              loaded.model_path))
    if isinstance(records, bytes):
      records = [records]
    outputs = loaded.tf_example_signature(tf.constant(list(records)))
    return {key: np.asarray(value) for key, value in outputs.items()}

  @property
  def variables(self):
    raise AttributeError(
        'ExportedSavedModelPredictor serves through SavedModel signatures; '
        'it holds no raw variables pytree (use ExportedModelPredictor for '
        'variable-level access).')

  @property
  def versioned_variables(self):
    return self.variables  # raises: no pytree behind a SavedModel
