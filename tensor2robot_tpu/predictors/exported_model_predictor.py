"""ExportedModelPredictor: serve from versioned export directories.

Parity target: /root/reference/predictors/exported_savedmodel_predictor.py:50-274.
Behaviors preserved:
  * poll the export root for the newest valid numeric version, skipping
    tmp-prefixed/partial dirs (:238-274), with a restore timeout (:120-148)
  * load feature/label specs from assets.extra/t2r_assets.pbtxt (:162-170)
  * global-step reconciliation from the artifact (:181-189)
  * retry on concurrent-write/GC races: a version vanishing mid-load falls
    back to the next-newest (:160-198)
  * serialized tf.Example receiver: ``predict_serialized`` parses record
    bytes with the spec-driven wire parser before the same feed

Two serving backends:
  * with a T2RModel: jitted preprocess+predict over restored variables
    (fresh XLA compile, fastest path on the serving host's own chip)
  * without any Python model code: the artifact's serialized StableHLO
    predict function (jax.export) — the SavedModel-like deployment mode
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

import jax
import numpy as np

from tensor2robot_tpu.export import export_generators
from tensor2robot_tpu.observability import get_registry
from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.reliability.logutil import log_warning
from tensor2robot_tpu.specs import assets as assets_lib
from tensor2robot_tpu.specs.struct import SpecStruct  # predict_serialized

_POLL_INTERVAL_SECS = 1.0
_WAIT_REPORT_INTERVAL_SECS = 10.0
EXPORT_WAIT_GAUGE = 'inference/export_wait_seconds'


class _Loaded:
  """One loaded export version, swapped in as a single reference.

  The pre-PR-8 implementation assigned serve_fn / variables / specs /
  version as SEPARATE attributes; a predict racing a hot-swap could pair
  the new serve function with the old variables (or parse request bytes
  with the old spec and feed the new weights) — a mixed-version result.
  Everything a serving call touches now rides one immutable snapshot
  (versioned-params contract, ISSUE 8; regression test in
  tests/test_predictors.py).
  """

  __slots__ = ('variables', 'exported_fn', 'serve_fn', 'raw_receivers',
               'feature_spec', 'label_spec', 'version', 'global_step',
               'model_path', 'parser')

  def __init__(self, variables, exported_fn, serve_fn, raw_receivers,
               feature_spec, label_spec, version, global_step, model_path):
    self.variables = variables
    self.exported_fn = exported_fn
    self.serve_fn = serve_fn
    self.raw_receivers = raw_receivers
    self.feature_spec = feature_spec
    self.label_spec = label_spec
    self.version = version
    self.global_step = global_step
    self.model_path = model_path
    # Derived lazily from THIS snapshot's spec on first
    # predict_serialized; racing builders construct equal parsers, so
    # last-write-wins is benign.
    self.parser = None


class ExportedModelPredictor(AbstractPredictor):
  """Serves the newest artifact under an export root directory."""

  def __init__(self,
               export_dir: str,
               t2r_model=None,
               timeout: float = 600.0):
    """Args:
      export_dir: the versioned export root (e.g.
        <model_dir>/export/latest_exporter).
      t2r_model: optional model for the recompile backend; None uses the
        artifact's serialized predict function.
      timeout: restore() polling budget in seconds (ref :57 — 600s).
    """
    self._export_dir = export_dir
    self._model = t2r_model
    self._timeout = timeout
    self._loaded: Optional[_Loaded] = None

  # -- restore ---------------------------------------------------------------

  def _try_load_version(self, version: int) -> bool:
    version_dir = os.path.join(self._export_dir, str(version))
    try:
      exported_fn = None
      if self._model is None:
        # Fail fast BEFORE the expensive variables restore: artifacts
        # whose serialization fell back to None can never serve model-less.
        fn_path = os.path.join(version_dir,
                               export_generators.PREDICT_FN_FILENAME)
        from jax import export as jax_export  # stable module, jax>=0.4.30

        with open(fn_path, 'rb') as f:
          exported_fn = jax_export.deserialize(f.read())
      feature_spec, label_spec, step = assets_lib.load_t2r_assets_from_file(
          os.path.join(version_dir, assets_lib.EXTRA_ASSETS_DIRECTORY,
                       assets_lib.T2R_ASSETS_FILENAME))
      variables = export_generators.load_exported_variables(version_dir)
    except (OSError, ValueError, FileNotFoundError):
      return False  # racing GC/partial write: caller falls back
    raw = bool(export_generators.load_serving_config(version_dir)
               .get('raw_receivers', False))
    previous = self._loaded
    serve_fn = None
    if self._model is not None:
      if previous is not None and previous.serve_fn is not None \
          and raw == previous.raw_receivers:
        serve_fn = previous.serve_fn  # same receiver mode: keep the jit
      else:
        # Honor the artifact's receiver mode: raw artifacts must NOT be
        # preprocessed again (ref abstract_export_generator.py:52).
        serve_fn = jax.jit(
            export_generators.make_serve_fn(self._model, raw_receivers=raw))
    if step is None:
      try:
        step = assets_lib.load_global_step_from_file(version_dir)
      except (OSError, ValueError):
        step = 0
    # The snapshot is fully built BEFORE the one reference assignment: a
    # concurrent predict sees either all of the old version or all of
    # the new one.
    self._loaded = _Loaded(
        variables=variables, exported_fn=exported_fn, serve_fn=serve_fn,
        raw_receivers=raw, feature_spec=feature_spec, label_spec=label_spec,
        version=version, global_step=int(step or 0), model_path=version_dir)
    return True

  def restore(self) -> bool:
    """Polls for a version newer than the current one (ref :120-148)."""
    # monotonic (matching CheckpointPredictor): a wall-clock jump must
    # not expire or extend the polling budget.
    wait_start = time.monotonic()
    deadline = wait_start + self._timeout
    next_report = wait_start + _WAIT_REPORT_INTERVAL_SECS
    # Labeled per export root: concurrent predictors must not clobber
    # each other's wait signal (see CheckpointPredictor.restore).
    wait_gauge = get_registry().gauge_family(
        EXPORT_WAIT_GAUGE, ('dir',)).series(self._export_dir)
    try:
      while True:
        versions = export_generators.list_exported_versions(self._export_dir)
        loaded = self._loaded
        fresh = [v for v in versions
                 if loaded is None or v > loaded.version]
        # Newest first; a vanished/partial dir falls back to the next one
        # (ref :160-198 retry semantics).
        for version in reversed(fresh):
          if self._try_load_version(version):
            return True
        if loaded is not None and versions:
          return True  # current version still newest and valid
        now = time.monotonic()
        if now >= next_report:
          elapsed = now - wait_start
          wait_gauge.set(elapsed)
          log_warning(
              'ExportedModelPredictor: still waiting for an export in %s '
              '(%.0fs elapsed, %.0fs until timeout).', self._export_dir,
              elapsed, max(deadline - now, 0.0))
          next_report = now + _WAIT_REPORT_INTERVAL_SECS
        if now > deadline:
          return False
        time.sleep(_POLL_INTERVAL_SECS)
    finally:
      wait_gauge.set(0.0)

  # -- serving ---------------------------------------------------------------

  def _loaded_snapshot(self) -> _Loaded:
    loaded = self._loaded  # ONE read; restore() swaps the whole reference
    if loaded is None:
      raise ValueError('The predictor has not been restored yet.')
    return loaded

  @property
  def variables(self):
    """The restored variables pytree (for custom jitted serving paths,
    e.g. DeviceCEMPolicy's one-dispatch CEM — checkpoint_predictor parity)."""
    return self._loaded_snapshot().variables

  @property
  def versioned_variables(self):
    """``(version, variables)`` from one atomic snapshot read — what a
    serving hot-swap consumes (PolicyServer.swap_from_predictor)."""
    loaded = self._loaded_snapshot()
    return loaded.version, loaded.variables

  @staticmethod
  def _predict_from(loaded: _Loaded, features: Dict[str, np.ndarray]
                    ) -> Dict[str, np.ndarray]:
    if loaded.serve_fn is not None:
      outputs = loaded.serve_fn(loaded.variables, dict(features))
    else:
      outputs = loaded.exported_fn.call(loaded.variables, dict(features))
    return {k: np.asarray(v) for k, v in jax.device_get(outputs).items()}

  def predict_versioned(self, features: Dict[str, np.ndarray]):
    loaded = self._loaded_snapshot()
    return self._predict_from(loaded, features), loaded.version

  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    return self.predict_versioned(features)[0]

  def predict_serialized(self, records) -> Dict[str, np.ndarray]:
    """tf.Example receiver: record bytes -> parse by spec -> predict.

    ref default_export_generator.py:104-138 (the tf_example receiver).
    The parser, spec, and weights all come from ONE snapshot — request
    bytes can never be parsed with one version's spec and scored with
    another's weights.
    """
    loaded = self._loaded_snapshot()
    if loaded.parser is None:
      from tensor2robot_tpu.data.parser import ExampleParser  # lazy: serving
      loaded.parser = ExampleParser(loaded.feature_spec, SpecStruct())
    if isinstance(records, bytes):
      records = [records]
    features, _ = loaded.parser.parse_batch(records)
    return self._predict_from(loaded, features.to_dict())

  def get_feature_specification(self):
    return self._loaded_snapshot().feature_spec

  def get_label_specification(self):
    return self._loaded_snapshot().label_spec

  @property
  def is_loaded(self) -> bool:
    return self._loaded is not None

  @property
  def model_version(self) -> int:
    loaded = self._loaded
    return loaded.version if loaded is not None else 0

  @property
  def global_step(self) -> int:
    loaded = self._loaded
    return loaded.global_step if loaded is not None else 0

  @property
  def model_path(self) -> str:
    loaded = self._loaded
    return loaded.model_path if loaded is not None else ''

  def close(self) -> None:
    # Dropping the snapshot also resets version tracking: a closed
    # predictor must not short-circuit a later restore() into "current
    # version still newest and valid" while holding no loaded state.
    self._loaded = None
