"""ExportedModelPredictor: serve from versioned export directories.

Parity target: /root/reference/predictors/exported_savedmodel_predictor.py:50-274.
Behaviors preserved:
  * poll the export root for the newest valid numeric version, skipping
    tmp-prefixed/partial dirs (:238-274), with a restore timeout (:120-148)
  * load feature/label specs from assets.extra/t2r_assets.pbtxt (:162-170)
  * global-step reconciliation from the artifact (:181-189)
  * retry on concurrent-write/GC races: a version vanishing mid-load falls
    back to the next-newest (:160-198)
  * serialized tf.Example receiver: ``predict_serialized`` parses record
    bytes with the spec-driven wire parser before the same feed

Two serving backends:
  * with a T2RModel: jitted preprocess+predict over restored variables
    (fresh XLA compile, fastest path on the serving host's own chip)
  * without any Python model code: the artifact's serialized StableHLO
    predict function (jax.export) — the SavedModel-like deployment mode
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

import jax
import numpy as np

from tensor2robot_tpu.export import export_generators
from tensor2robot_tpu.observability import get_registry
from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.reliability.logutil import log_warning
from tensor2robot_tpu.specs import assets as assets_lib
from tensor2robot_tpu.specs.struct import SpecStruct  # predict_serialized

_POLL_INTERVAL_SECS = 1.0
_WAIT_REPORT_INTERVAL_SECS = 10.0
EXPORT_WAIT_GAUGE = 'inference/export_wait_seconds'


class ExportedModelPredictor(AbstractPredictor):
  """Serves the newest artifact under an export root directory."""

  def __init__(self,
               export_dir: str,
               t2r_model=None,
               timeout: float = 600.0):
    """Args:
      export_dir: the versioned export root (e.g.
        <model_dir>/export/latest_exporter).
      t2r_model: optional model for the recompile backend; None uses the
        artifact's serialized predict function.
      timeout: restore() polling budget in seconds (ref :57 — 600s).
    """
    self._export_dir = export_dir
    self._model = t2r_model
    self._timeout = timeout
    self._feature_spec = None
    self._label_spec = None
    self._variables = None
    self._exported_fn = None
    self._serve_fn = None
    self._parser = None
    self._version: Optional[int] = None
    self._global_step = 0
    self._model_path = ''
    self._raw_receivers = False

  # -- restore ---------------------------------------------------------------

  def _try_load_version(self, version: int) -> bool:
    version_dir = os.path.join(self._export_dir, str(version))
    try:
      exported_fn = None
      if self._model is None:
        # Fail fast BEFORE the expensive variables restore: artifacts
        # whose serialization fell back to None can never serve model-less.
        fn_path = os.path.join(version_dir,
                               export_generators.PREDICT_FN_FILENAME)
        from jax import export as jax_export  # stable module, jax>=0.4.30

        with open(fn_path, 'rb') as f:
          exported_fn = jax_export.deserialize(f.read())
      feature_spec, label_spec, step = assets_lib.load_t2r_assets_from_file(
          os.path.join(version_dir, assets_lib.EXTRA_ASSETS_DIRECTORY,
                       assets_lib.T2R_ASSETS_FILENAME))
      variables = export_generators.load_exported_variables(version_dir)
    except (OSError, ValueError, FileNotFoundError):
      return False  # racing GC/partial write: caller falls back
    raw = bool(export_generators.load_serving_config(version_dir)
               .get('raw_receivers', False))
    if self._model is not None and (self._serve_fn is None or
                                    raw != self._raw_receivers):
      # Honor the artifact's receiver mode: raw artifacts must NOT be
      # preprocessed again (ref abstract_export_generator.py:52).
      self._serve_fn = jax.jit(
          export_generators.make_serve_fn(self._model, raw_receivers=raw))
    self._raw_receivers = raw
    self._feature_spec = feature_spec
    self._label_spec = label_spec
    self._variables = variables
    self._exported_fn = exported_fn
    self._version = version
    if step is None:
      try:
        step = assets_lib.load_global_step_from_file(version_dir)
      except (OSError, ValueError):
        step = 0
    self._global_step = int(step or 0)
    self._model_path = version_dir
    self._parser = None  # re-derive from the new specs on demand
    return True

  def restore(self) -> bool:
    """Polls for a version newer than the current one (ref :120-148)."""
    # monotonic (matching CheckpointPredictor): a wall-clock jump must
    # not expire or extend the polling budget.
    wait_start = time.monotonic()
    deadline = wait_start + self._timeout
    next_report = wait_start + _WAIT_REPORT_INTERVAL_SECS
    # Labeled per export root: concurrent predictors must not clobber
    # each other's wait signal (see CheckpointPredictor.restore).
    wait_gauge = get_registry().gauge_family(
        EXPORT_WAIT_GAUGE, ('dir',)).series(self._export_dir)
    try:
      while True:
        versions = export_generators.list_exported_versions(self._export_dir)
        fresh = [v for v in versions
                 if self._version is None or v > self._version]
        # Newest first; a vanished/partial dir falls back to the next one
        # (ref :160-198 retry semantics).
        for version in reversed(fresh):
          if self._try_load_version(version):
            return True
        if self._version is not None and versions:
          return True  # current version still newest and valid
        now = time.monotonic()
        if now >= next_report:
          elapsed = now - wait_start
          wait_gauge.set(elapsed)
          log_warning(
              'ExportedModelPredictor: still waiting for an export in %s '
              '(%.0fs elapsed, %.0fs until timeout).', self._export_dir,
              elapsed, max(deadline - now, 0.0))
          next_report = now + _WAIT_REPORT_INTERVAL_SECS
        if now > deadline:
          return False
        time.sleep(_POLL_INTERVAL_SECS)
    finally:
      wait_gauge.set(0.0)

  # -- serving ---------------------------------------------------------------

  @property
  def variables(self):
    """The restored variables pytree (for custom jitted serving paths,
    e.g. DeviceCEMPolicy's one-dispatch CEM — checkpoint_predictor parity)."""
    self.assert_is_loaded()
    return self._variables

  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    self.assert_is_loaded()
    if self._serve_fn is not None:
      outputs = self._serve_fn(self._variables, dict(features))
    else:
      outputs = self._exported_fn.call(self._variables, dict(features))
    return {k: np.asarray(v) for k, v in jax.device_get(outputs).items()}

  def predict_serialized(self, records) -> Dict[str, np.ndarray]:
    """tf.Example receiver: record bytes -> parse by spec -> predict.

    ref default_export_generator.py:104-138 (the tf_example receiver).
    """
    self.assert_is_loaded()
    if self._parser is None:
      from tensor2robot_tpu.data.parser import ExampleParser  # lazy: serving
      self._parser = ExampleParser(self._feature_spec, SpecStruct())
    if isinstance(records, bytes):
      records = [records]
    features, _ = self._parser.parse_batch(records)
    return self.predict(features.to_dict())

  def get_feature_specification(self):
    self.assert_is_loaded()
    return self._feature_spec

  def get_label_specification(self):
    self.assert_is_loaded()
    return self._label_spec

  @property
  def is_loaded(self) -> bool:
    return self._variables is not None

  @property
  def model_version(self) -> int:
    return self._version or 0

  @property
  def global_step(self) -> int:
    return self._global_step

  @property
  def model_path(self) -> str:
    return self._model_path

  def close(self) -> None:
    self._variables = None
    self._exported_fn = None
    # Reset version tracking: a closed predictor must not short-circuit a
    # later restore() into "current version still newest and valid" while
    # holding no loaded state.
    self._version = None
