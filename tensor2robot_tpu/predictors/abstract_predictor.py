"""AbstractPredictor: numpy-in / numpy-out model serving interface.

Parity target: /root/reference/predictors/abstract_predictor.py:32-87. The
contract robot-side code programs against: ``predict(features_dict)``,
spec getters, ``restore``/``init_randomly``/``close``, and version metadata.

Every concrete predictor is instrumented automatically (ISSUE 3): the
base class wraps each subclass's own ``predict``/``restore`` at class
creation, so robot-control-loop latency lands in the registry histogram
``inference/latency_ms/<PredictorClass>`` (p50/p95/p99 via
``Histogram.summary``) and model refreshes in
``inference/restores/<PredictorClass>/<outcome>`` — with zero per-call
work in subclasses and no way for a new predictor to forget the wiring.
"""

from __future__ import annotations

import abc
import functools
import threading
import time
from typing import Dict, Optional

import numpy as np

from tensor2robot_tpu.observability import (
    DEFAULT_LATENCY_BUCKETS_MS,
    get_registry,
)

INFERENCE_LATENCY_HISTOGRAM = 'inference/latency_ms'
INFERENCE_RESTORES_COUNTER = 'inference/restores'
INFERENCE_ERRORS_COUNTER = 'inference/errors'

# Reentrancy guard: predict_serialized usually routes through predict();
# only the OUTERMOST instrumented call on a thread records, so one robot
# request is one histogram observation, never two.
_call_depth = threading.local()


# (registry, class name) -> resolved series. The serving hot path must
# not pay a registry lock + family lookup per call ("resolve labeled
# series once outside loops", registry.py). Keyed by the registry OBJECT
# (identity hash, strong ref — ids are never recycled under the cache),
# so a swapped test registry never receives another registry's series.
_SERIES_CACHE: Dict[tuple, object] = {}


def _latency_histogram(predictor_name: str):
  """The per-predictor-class latency series (label = concrete class)."""
  registry = get_registry()
  key = (registry, predictor_name)
  series = _SERIES_CACHE.get(key)
  if series is None:
    series = registry.histogram_family(
        INFERENCE_LATENCY_HISTOGRAM, ('predictor',),
        bounds=DEFAULT_LATENCY_BUCKETS_MS).series(predictor_name)
    _SERIES_CACHE[key] = series
  return series


def _instrument_predict(fn):
  """Times successful predict-path calls; failures count separately (an
  exploding latency histogram and an error burst are different pages)."""

  @functools.wraps(fn)
  def wrapper(self, features, *args, **kwargs):
    name = type(self).__name__
    depth = getattr(_call_depth, 'value', 0)
    _call_depth.value = depth + 1
    start = time.perf_counter()
    try:
      outputs = fn(self, features, *args, **kwargs)
    except Exception:
      if depth == 0:
        get_registry().counter_family(
            INFERENCE_ERRORS_COUNTER, ('predictor',)).series(name).inc()
      raise
    finally:
      _call_depth.value = depth
    if depth == 0:
      _latency_histogram(name).record((time.perf_counter() - start) * 1e3)
    return outputs

  wrapper._t2r_instrumented = True  # noqa: SLF001 — idempotence marker
  return wrapper


def _instrument_restore(fn):
  """Counts restore/refresh attempts by outcome (success vs timeout)."""

  @functools.wraps(fn)
  def wrapper(self, *args, **kwargs):
    result = fn(self, *args, **kwargs)
    get_registry().counter_family(
        INFERENCE_RESTORES_COUNTER, ('predictor', 'outcome')).series(
            type(self).__name__,
            'timeout' if result is False else 'success').inc()
    return result

  wrapper._t2r_instrumented = True  # noqa: SLF001
  return wrapper


class AbstractPredictor(abc.ABC):
  """Loads a model and exposes a predict function (ref :32)."""

  def __init_subclass__(cls, **kwargs):
    # Wrap only methods DEFINED on this subclass: inherited methods were
    # wrapped on their defining class (the label reads the runtime type,
    # so an inheriting predictor still reports under its own name).
    # predict_serialized is wrapped too — a SavedModel predictor serving
    # tf.Example bytes never touches predict(); the thread-local depth
    # guard keeps implementations that DO route through predict() from
    # double-counting one request.
    super().__init_subclass__(**kwargs)
    for method, instrument in (('predict', _instrument_predict),
                               ('predict_serialized', _instrument_predict),
                               ('restore', _instrument_restore)):
      fn = cls.__dict__.get(method)
      if fn is not None and callable(fn) and not getattr(
          fn, '_t2r_instrumented', False):
        setattr(cls, method, instrument(fn))

  @abc.abstractmethod
  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Runs the model on a dict of feature arrays (ref :40)."""

  def predict_versioned(self, features: Dict[str, np.ndarray]):
    """``(outputs, model_version)`` where BOTH come from one atomic read
    of the loaded state — the versioned-params contract the serving
    layer's hot-swap relies on (ISSUE 8): a concurrent ``restore`` must
    never yield outputs from one version labeled with another.

    The base implementation is only version-consistent when the subclass
    keeps its loaded state in a single atomically-swapped snapshot;
    CheckpointPredictor and ExportedModelPredictor do (and a regression
    test hammers them, tests/test_predictors.py).
    """
    return self.predict(features), self.model_version

  @abc.abstractmethod
  def get_feature_specification(self):
    """The input features required for prediction (ref :51)."""

  def get_label_specification(self):
    """Optional labels for evaluation of the model (ref :54)."""
    return None

  @abc.abstractmethod
  def restore(self) -> bool:
    """Restores parameters from the latest available data (ref :60).

    Returns True on success (the reference raises/loops; a bool lets the
    collect loop decide whether to keep polling).
    """

  def init_randomly(self) -> None:
    """Initializes parameters randomly, for tests and cold starts (ref :63)."""

  @abc.abstractmethod
  def close(self) -> None:
    """Releases all handles (ref :67)."""

  def assert_is_loaded(self) -> None:
    """Raises ValueError if restore/init has not happened yet (ref :71)."""
    if not self.is_loaded:
      raise ValueError('The predictor has not been restored yet.')

  @property
  def is_loaded(self) -> bool:
    return False

  @property
  def model_version(self) -> int:
    """The version of the model currently in use (ref :75)."""
    return 0

  @property
  def global_step(self) -> int:
    """The global step of the model currently in use (ref :80)."""
    return 0

  @property
  def model_path(self) -> str:
    """The path of the model currently in use (ref :85)."""
    return ''
