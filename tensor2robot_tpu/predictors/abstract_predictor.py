"""AbstractPredictor: numpy-in / numpy-out model serving interface.

Parity target: /root/reference/predictors/abstract_predictor.py:32-87. The
contract robot-side code programs against: ``predict(features_dict)``,
spec getters, ``restore``/``init_randomly``/``close``, and version metadata.
"""

from __future__ import annotations

import abc
from typing import Dict, Optional

import numpy as np


class AbstractPredictor(abc.ABC):
  """Loads a model and exposes a predict function (ref :32)."""

  @abc.abstractmethod
  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Runs the model on a dict of feature arrays (ref :40)."""

  @abc.abstractmethod
  def get_feature_specification(self):
    """The input features required for prediction (ref :51)."""

  def get_label_specification(self):
    """Optional labels for evaluation of the model (ref :54)."""
    return None

  @abc.abstractmethod
  def restore(self) -> bool:
    """Restores parameters from the latest available data (ref :60).

    Returns True on success (the reference raises/loops; a bool lets the
    collect loop decide whether to keep polling).
    """

  def init_randomly(self) -> None:
    """Initializes parameters randomly, for tests and cold starts (ref :63)."""

  @abc.abstractmethod
  def close(self) -> None:
    """Releases all handles (ref :67)."""

  def assert_is_loaded(self) -> None:
    """Raises ValueError if restore/init has not happened yet (ref :71)."""
    if not self.is_loaded:
      raise ValueError('The predictor has not been restored yet.')

  @property
  def is_loaded(self) -> bool:
    return False

  @property
  def model_version(self) -> int:
    """The version of the model currently in use (ref :75)."""
    return 0

  @property
  def global_step(self) -> int:
    """The global step of the model currently in use (ref :80)."""
    return 0

  @property
  def model_path(self) -> str:
    """The path of the model currently in use (ref :85)."""
    return ''
