"""Predictors: load trained models and expose numpy predict functions."""

from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.predictors.checkpoint_predictor import (
    CheckpointPredictor,
)
from tensor2robot_tpu.predictors.exported_model_predictor import (
    ExportedModelPredictor,
)
from tensor2robot_tpu.predictors.exported_savedmodel_predictor import (
    ExportedSavedModelPredictor,
)

__all__ = [
    'AbstractPredictor',
    'CheckpointPredictor',
    'ExportedModelPredictor',
    'ExportedSavedModelPredictor',
]
