"""CheckpointPredictor: serve straight from training checkpoints.

Parity target: /root/reference/predictors/checkpoint_predictor.py:39-212.
The reference rebuilds the PREDICT graph from the T2RModel in its own
tf.Graph with placeholders (:69-102), busy-waits for checkpoints (:134-179),
and serves via session.run (:106-117). Here the model's pure predict step is
jitted once; ``restore`` polls the Orbax checkpoint directory and swaps the
variables pytree — no graph rebuild, no session.
"""

from __future__ import annotations

import time
from typing import Any, Dict, NamedTuple, Optional

import jax
import numpy as np

from tensor2robot_tpu.export.export_generators import make_serve_fn
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.observability import get_registry
from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.reliability.errors import CHECKPOINT_SKIP_ERRORS
from tensor2robot_tpu.reliability.logutil import log_warning
from tensor2robot_tpu.specs import generators as spec_generators
from tensor2robot_tpu.trainer import checkpointing

_POLL_INTERVAL_SECS = 1.0
# How often the (otherwise silent) checkpoint wait announces itself. A
# robot host stuck here looks exactly like a healthy idle one without
# the periodic log + gauge.
_WAIT_REPORT_INTERVAL_SECS = 10.0
CHECKPOINT_WAIT_GAUGE = 'inference/checkpoint_wait_seconds'


class _Loaded(NamedTuple):
  """One restored model version, swapped in as a single reference.

  The versioned-params contract (ISSUE 8): every loaded field a serving
  call needs lives in ONE immutable snapshot assigned atomically, so a
  concurrent ``restore`` can never interleave — a predict that started
  on step N finishes entirely on step N, and ``predict_versioned``
  labels its outputs with the step that actually produced them.
  """

  variables: Any
  step: int


class CheckpointPredictor(AbstractPredictor):
  """Polls <checkpoint_dir>/checkpoints and serves the newest step."""

  def __init__(self,
               t2r_model,
               checkpoint_dir: Optional[str] = None,
               timeout: float = 600.0):
    """Args:
      t2r_model: the model whose predict path to serve.
      checkpoint_dir: the trainer's model_dir. None => init_randomly only
        (ref checkpoint_predictor.py:47 allows checkpoint-less predictors).
      timeout: max seconds restore() busy-waits for a first checkpoint
        (ref :47 — 600s default).
    """
    self._model = t2r_model
    self._checkpoint_dir = checkpoint_dir
    self._timeout = timeout
    self._loaded: Optional[_Loaded] = None
    # The one shared serving path (preprocess + predict_step), jitted once.
    self._serve_fn = jax.jit(make_serve_fn(t2r_model))

  # -- loading ---------------------------------------------------------------

  def init_randomly(self) -> None:
    """ref :121 — random init from the model's specs, no checkpoint."""
    feature_spec = self._model.get_feature_specification_for_packing(
        ModeKeys.PREDICT)
    features = spec_generators.make_random_numpy(feature_spec, batch_size=1)
    self._loaded = _Loaded(
        variables=self._model.init_variables(
            jax.random.PRNGKey(0), features, None, ModeKeys.PREDICT),
        step=0)

  def restore(self) -> bool:
    """Busy-waits for a (new) checkpoint, then loads it (ref :134-179).

    The CheckpointManager retries transient save/restore failures with
    backoff underneath; a checkpoint that still fails to load (half-written
    by the trainer, deleted by retention GC mid-read) is skipped and the
    loop keeps polling until the timeout — a robot-side consumer must not
    die because it raced the trainer's filesystem commits.
    """
    if self._checkpoint_dir is None:
      raise ValueError('CheckpointPredictor constructed without a '
                       'checkpoint_dir; call init_randomly() instead.')
    # monotonic: a wall-clock jump must not expire (or extend) the wait.
    wait_start = time.monotonic()
    deadline = wait_start + self._timeout
    next_report = wait_start + _WAIT_REPORT_INTERVAL_SECS
    # Labeled per watched directory: one predictor finishing its wait
    # must not zero out another instance's in-progress wait signal.
    wait_gauge = get_registry().gauge_family(
        CHECKPOINT_WAIT_GAUGE, ('dir',)).series(self._checkpoint_dir)
    try:
      while True:
        steps = checkpointing.all_checkpoint_steps(self._checkpoint_dir)
        loaded = self._loaded
        floor = loaded.step if loaded is not None else -1
        # Newest first, but never DOWNGRADE below what is already loaded: a
        # permanently damaged newest step must not block serving when older
        # intact checkpoints sit in the same directory.
        candidates = [s for s in steps if s > floor]
        if not candidates and loaded is not None and steps:
          return True  # nothing newer; current state is still valid
        for step in candidates:
          try:
            return self._load_step(step)
          except CHECKPOINT_SKIP_ERRORS as e:
            log_warning(
                'CheckpointPredictor: step %d in %s failed to restore (%s); '
                'trying an older checkpoint.', step, self._checkpoint_dir, e)
        now = time.monotonic()
        if now >= next_report:
          # Waiting is expected (the trainer may simply not have committed
          # yet) but must never be silent: a wedged trainer and a healthy
          # cold start look identical without this heartbeat.
          elapsed = now - wait_start
          wait_gauge.set(elapsed)
          log_warning(
              'CheckpointPredictor: still waiting for a checkpoint in %s '
              '(%.0fs elapsed, %.0fs until timeout).', self._checkpoint_dir,
              elapsed, max(deadline - now, 0.0))
          next_report = now + _WAIT_REPORT_INTERVAL_SECS
        if now > deadline:
          return False
        time.sleep(_POLL_INTERVAL_SECS)
    finally:
      # The wait ended (loaded, still-valid, or timed out): stop
      # advertising a stale in-progress wait to dashboards.
      wait_gauge.set(0.0)

  def _load_step(self, step: int) -> bool:
    # quarantine_damaged=False: this is a read-only consumer of another
    # process's training directory; it must never rename files there.
    manager = checkpointing.CheckpointManager(self._checkpoint_dir,
                                              async_checkpoints=False,
                                              quarantine_damaged=False)
    try:
      restored = manager.restore(None, step=step)
    finally:
      manager.close()
    variables = {'params': restored['params'],
                 **(restored.get('model_state') or {})}
    if restored.get('avg_params') is not None:
      variables['avg_params'] = restored['avg_params']
    # One atomic reference swap: concurrent predict calls see either the
    # whole old version or the whole new one, never a mix.
    self._loaded = _Loaded(variables=variables, step=step)
    return True

  # -- serving ---------------------------------------------------------------

  def _loaded_snapshot(self) -> _Loaded:
    loaded = self._loaded  # ONE read; restore() swaps the whole reference
    if loaded is None:
      raise ValueError('The predictor has not been restored yet.')
    return loaded

  @property
  def variables(self):
    """The restored variables pytree (for custom jitted serving paths)."""
    return self._loaded_snapshot().variables

  @property
  def versioned_variables(self):
    """``(version, variables)`` from one atomic snapshot read — what a
    serving hot-swap consumes (PolicyServer.swap_from_predictor)."""
    loaded = self._loaded_snapshot()
    return loaded.step, loaded.variables

  def predict_versioned(self, features: Dict[str, np.ndarray]):
    loaded = self._loaded_snapshot()
    outputs = self._serve_fn(loaded.variables, dict(features))
    return ({k: np.asarray(v) for k, v in jax.device_get(outputs).items()},
            loaded.step)

  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    return self.predict_versioned(features)[0]

  def get_feature_specification(self):
    return self._model.preprocessor.get_in_feature_specification(
        ModeKeys.PREDICT)

  def get_label_specification(self):
    return self._model.get_label_specification(ModeKeys.PREDICT)

  @property
  def is_loaded(self) -> bool:
    return self._loaded is not None

  @property
  def global_step(self) -> int:
    loaded = self._loaded
    return loaded.step if loaded is not None else 0

  @property
  def model_version(self) -> int:
    return self.global_step

  @property
  def model_path(self) -> str:
    return self._checkpoint_dir or ''

  def close(self) -> None:
    self._loaded = None
